"""Chaos-matrix acceptance for the resilience subsystem.

Each scenario (flexflow_tpu/runtime/chaos.py) injects a fault into a
``steps_per_call=8`` superstep run — raised fault, NaN batch, NaN
loss, SIGTERM preemption, checkpoint corruption — and must recover and
finish with a loss trajectory **bit-identical** to the unfaulted run;
the force-save scenario kills a crash-safe replace between each phase
and must always find a restorable checkpoint.  The same matrix runs
standalone via ``tools/chaos_smoke.py``.
"""

import pytest

from flexflow_tpu.runtime import chaos


@pytest.fixture(scope="module")
def chaos_root(tmp_path_factory):
    # One root for the whole module: the unfaulted baseline trajectory
    # is computed once and shared by every scenario.
    return str(tmp_path_factory.mktemp("chaos"))


# The multi-host rig scenarios spawn real 2-process jax.distributed
# worlds (generations are jit-compile dominated, ~2 min together), the
# speculation scenario compiles spec + plain decode programs for
# padded AND paged layouts, the fleet scenario compiles three replica
# engines, and the prefix-donor scenario compiles padded + two paged
# serving stacks — slow-marked so the tier-1 `-m 'not slow'` budget
# holds; the targeted `pytest tests/test_chaos.py` run and
# `tools/chaos_smoke.py` exercise them.
_SLOW_SCENARIOS = {"host_loss", "coordinator_loss", "serving_spec_fault",
                   "replica_loss", "prefix_donor_eviction"}


@pytest.mark.parametrize("name", [
    pytest.param(n, marks=pytest.mark.slow) if n in _SLOW_SCENARIOS else n
    for n in chaos.SCENARIOS
])
def test_chaos_scenario(chaos_root, name):
    ok, detail = chaos.SCENARIOS[name](chaos_root)
    assert ok, detail
