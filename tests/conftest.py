"""Test harness: force an 8-device virtual CPU mesh.

The reference exercises multi-GPU logic without a cluster via Legion's
proc abstraction; our analogue (SURVEY.md §4) is jax's host-platform
device multiplexing.  Must run before jax initializes its backend.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon sitecustomize force-registers the TPU backend and overrides
# jax_platforms at import; override it back before any backend init.
jax.config.update("jax_platforms", "cpu")

# NOTE: do NOT wire jax's persistent compilation cache
# (jax_compilation_cache_dir) into this suite to speed up the one-core
# box: with min_compile_time 0 the XLA:CPU executable deserializer
# SEGFAULTS deterministically in the orbax-heavy checkpoint tests
# (jax 0.4.37), and with a safe 1.0s threshold the warm-run saving is
# ~10% — not worth the crash surface.  Measured 2026-08-04 (ISSUE 3).

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
