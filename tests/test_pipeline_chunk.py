"""Chunked-scan pipeline dispatch + pipeline supersteps (ISSUE 3).

The invariants pinned here extend the superstep family
(``tests/test_superstep.py``) to the layer-wise runtime:

- **Chunk invariance** — ``chunk=c`` folds each stage's per-microbatch
  fwd/bwd programs into ONE jitted ``lax.scan`` over ``c`` stacked
  microbatches; loss AND param trajectories must be BIT-IDENTICAL
  across ``c`` (the scan carries the running gradient/metric sums, so
  accumulation order is microbatch order regardless of chunking).
- **Dispatch accounting** — ``last_schedule`` records one event per
  host program: ``2*S*ceil(m/c)`` per step, dependency-valid at chunk
  granularity.
- **Pipeline supersteps** — ``Trainer.fit(steps_per_call=k)`` on a
  PipelineExecutor dispatches k steps back-to-back under ONE
  ``jax.device_get`` fence; trajectories bit-identical to k=1.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.graph import FFModel
from flexflow_tpu.optim import SGDOptimizer
from flexflow_tpu.parallel.strategy import ParallelConfig, StrategyStore
from flexflow_tpu.runtime.pipeline import PipelineExecutor
from flexflow_tpu.runtime.trainer import Trainer


def _model(batch=16, dropout=0.0):
    ff = FFModel(FFConfig(batch_size=batch))
    x = ff.create_tensor((batch, 12), name="x")
    lbl = ff.create_tensor((batch,), dtype=jnp.int32, name="label")
    t = ff.dense(x, 16, activation="relu", name="enc0")
    t = ff.dense(t, 16, activation="relu", name="enc1")
    if dropout > 0.0:
        t = ff.dropout(t, rate=dropout, name="drop")
    t = ff.dense(t, 16, activation="relu", name="dec0")
    t = ff.dense(t, 4, activation=None, name="dec1")
    ff.softmax(t, lbl, name="softmax")
    return ff


def _store(nd=8, with_dropout=False):
    enc = tuple(range(nd // 2))
    dec = tuple(range(nd // 2, nd))
    store = StrategyStore(nd)
    for n in ("enc0", "enc1"):
        store.set(n, ParallelConfig(n=len(enc), device_ids=enc))
    names = ("drop",) if with_dropout else ()
    for n in names + ("dec0", "dec1", "softmax"):
        store.set(n, ParallelConfig(n=len(dec), device_ids=dec))
    return store


def _batches(n, batch=16, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {
            "x": rng.standard_normal((batch, 12)).astype(np.float32),
            "label": rng.integers(0, 4, size=(batch,)).astype(np.int32),
        }
        for _ in range(n)
    ]


def _pipe_fresh(microbatches=4, chunk=1, schedule="1f1b", clip=0.0,
                dropout=0.0):
    cfg = FFConfig(batch_size=16, clip_norm=clip)
    return PipelineExecutor(
        _model(dropout=dropout), _store(with_dropout=dropout > 0.0),
        config=cfg, optimizer=SGDOptimizer(lr=0.1, momentum=0.9),
        microbatches=microbatches, schedule=schedule, chunk=chunk,
    )


@functools.lru_cache(maxsize=None)
def _pipe(microbatches=4, chunk=1, schedule="1f1b", clip=0.0, dropout=0.0):
    """Executors are stateless between train_step calls (params are
    explicit), so tests sharing a config share its compiled stage
    programs — the suite runs on one core and compiles dominate."""
    return _pipe_fresh(microbatches, chunk, schedule, clip, dropout)


def _run(pipe, batches):
    params, opt_state, state = pipe.init(seed=0)
    losses = []
    for b in batches:
        params, opt_state, state, m = pipe.train_step(
            params, opt_state, state, pipe.shard_batch(b)
        )
        losses.append(np.asarray(jax.device_get(m["train_loss"])))
    return np.array(losses), jax.device_get(params)


def _assert_bit_identical(run_a, run_b, msg=""):
    losses_a, params_a = run_a
    losses_b, params_b = run_b
    np.testing.assert_array_equal(losses_a, losses_b, err_msg=msg)
    for a, b in zip(jax.tree.leaves(params_a), jax.tree.leaves(params_b)):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=msg
        )


# -- chunk invariance ---------------------------------------------------------


@pytest.mark.parametrize("chunk", [2, 4])
def test_chunked_bit_identical_to_event_loop(chunk):
    """c in {2, m}: trajectories bit-identical to the c=1 per-microbatch
    event loop (the acceptance-criterion invariant)."""
    batches = _batches(3)
    ref = _run(_pipe(chunk=1), batches)
    got = _run(_pipe(chunk=chunk), batches)
    _assert_bit_identical(ref, got, f"chunk={chunk}")


def test_chunked_nondivisible_tail():
    """m=4, c=3: chunks of 3+1 microbatches — the short tail chunk is
    its own compiled scan length and numerics stay bit-identical."""
    batches = _batches(2)
    ref = _run(_pipe(chunk=1), batches)
    got = _run(_pipe(chunk=3), batches)
    _assert_bit_identical(ref, got, "chunk=3 (non-divisible)")


def test_chunked_schedule_invariant():
    """Chunked numerics are also schedule-invariant (1f1b vs gpipe at
    chunk granularity)."""
    batches = _batches(2)
    _assert_bit_identical(
        _run(_pipe(chunk=2, schedule="1f1b"), batches),
        _run(_pipe(chunk=2, schedule="gpipe"), batches),
    )


def test_chunked_clip_norm_bit_identical():
    """The batched clip-norm fence (ONE device_get of all S squared
    norms) preserves global-norm clipping numerics across chunking."""
    batches = _batches(2, seed=3)
    ref = _run(_pipe(chunk=1, clip=0.5), batches)
    got = _run(_pipe(chunk=4, clip=0.5), batches)
    _assert_bit_identical(ref, got, "clip_norm chunked")
    # And the clip actually engaged (scale < 1 at lr-sized grads).
    noclip = _run(_pipe(chunk=4), batches)
    assert not np.array_equal(
        jax.tree.leaves(ref[1])[0], jax.tree.leaves(noclip[1])[0]
    )


def test_chunked_dropout_rng_chain():
    """The stacked-prestate remat threads the dropout RNG chain through
    the scan exactly as the per-microbatch loop does."""
    batches = _batches(2)
    ref = _run(_pipe(chunk=1, dropout=0.5), batches)
    got = _run(_pipe(chunk=2, dropout=0.5), batches)
    _assert_bit_identical(ref, got, "dropout chunked")


def test_chunked_skip_connection(rng):
    """A stage-0 output consumed by TWO later stages: stacked cotangent
    contributions sum on the producer's mesh per chunk."""
    batch = 8
    ff = FFModel(FFConfig(batch_size=batch))
    x = ff.create_tensor((batch, 12), name="x")
    lbl = ff.create_tensor((batch,), dtype=jnp.int32, name="label")
    t0 = ff.dense(x, 8, activation="relu", name="s0")
    t1 = ff.dense(t0, 8, activation="relu", name="s1")
    t2 = ff.concat([t0, t1], axis=1, name="s2cat")
    t3 = ff.dense(t2, 4, activation=None, name="s2fc")
    ff.softmax(t3, lbl, name="softmax")
    store = StrategyStore(6)
    store.set("s0", ParallelConfig(n=2, device_ids=(0, 1)))
    store.set("s1", ParallelConfig(n=2, device_ids=(2, 3)))
    for name in ("s2cat", "s2fc", "softmax"):
        store.set(name, ParallelConfig(n=2, device_ids=(4, 5)))
    batch_data = {
        "x": rng.standard_normal((batch, 12)).astype(np.float32),
        "label": rng.integers(0, 4, size=(batch,)).astype(np.int32),
    }

    def run(chunk):
        pipe = PipelineExecutor(
            ff, store, optimizer=SGDOptimizer(lr=0.1),
            microbatches=2, chunk=chunk,
        )
        p, o, s = pipe.init(seed=0)
        p2, _, _, m = pipe.train_step(p, o, s, pipe.shard_batch(batch_data))
        return np.array(jax.device_get(m["train_loss"])), jax.device_get(p2)

    _assert_bit_identical(run(1), run(2), "skip connection chunked")


# -- dispatch accounting ------------------------------------------------------


@pytest.mark.parametrize("chunk,n_units", [(1, 4), (2, 2), (3, 2), (4, 1)])
def test_chunk_cuts_programs_per_step(chunk, n_units):
    """last_schedule records one event per host program: 2*S*ceil(m/c),
    dependency-valid at chunk granularity."""
    pipe = _pipe(microbatches=4, chunk=chunk)
    params, opt_state, state = pipe.init(seed=0)
    pipe.train_step(params, opt_state, state,
                    pipe.shard_batch(_batches(1)[0]))
    S = len(pipe.stages)
    ev = pipe.last_schedule
    assert len(ev) == 2 * S * n_units, (chunk, ev)
    assert ev == pipe.build_schedule(S, n_units)
    pos = {e: i for i, e in enumerate(ev)}
    for kind, si, ci in ev:
        if kind == "F" and si > 0:
            assert pos[("F", si - 1, ci)] < pos[("F", si, ci)]
        if kind == "B":
            assert pos[("F", si, ci)] < pos[("B", si, ci)]
            if si < S - 1:
                assert pos[("B", si + 1, ci)] < pos[("B", si, ci)]


def test_chunk_clamped_to_microbatches(caplog):
    import logging

    with caplog.at_level(logging.WARNING, logger="ff.pipeline"):
        pipe = _pipe_fresh(microbatches=2, chunk=8)
    assert pipe.chunk == 2
    assert any("clamping" in r.message for r in caplog.records)
    with pytest.raises(ValueError, match="chunk"):
        _pipe_fresh(chunk=0)


# -- pipeline supersteps ------------------------------------------------------


def test_pipeline_superstep_bit_identical():
    """k pipeline steps under ONE fence: loss/param trajectories
    bit-identical to steps_per_call=1, for c=1 and c=m."""
    n_steps, k = 6, 3
    batches = _batches(n_steps + 1)  # +1 warmup

    def fit(steps_per_call, chunk):
        pipe = _pipe(chunk=chunk)
        tr = Trainer(pipe)
        stats = tr.fit(
            iterations=n_steps, warmup=1, steps_per_call=steps_per_call,
            batches=iter(batches), prefetch=0,
        )
        return stats, jax.device_get(tr.final[0])

    s1, p1 = fit(1, 1)
    sk, pk = fit(k, 1)
    skc, pkc = fit(k, 4)
    assert sk["steps_per_call"] == k and sk["supersteps"] == 2
    for got in (pk, pkc):
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pipeline_superstep_remainder_and_stats():
    """iterations not divisible by k: the tail superstep is shorter;
    stats account every step exactly once (no warmup rounding on the
    pipeline path — there is no k-sized compiled program)."""
    pipe = _pipe(chunk=4)
    stats = Trainer(pipe).fit(iterations=5, warmup=2, steps_per_call=2)
    assert stats["iterations"] == 5
    assert stats["steps_per_call"] == 2
    assert stats["supersteps"] == 3  # 2 + 2 + 1
    assert stats["samples_per_s"] > 0


def test_pipeline_superstep_clamps(caplog):
    import logging

    from flexflow_tpu.runtime.trainer import MAX_STEPS_PER_CALL

    pipe = _pipe(chunk=4)
    with caplog.at_level(logging.WARNING, logger="ff.trainer"):
        stats = Trainer(pipe).fit(
            iterations=2, warmup=0, steps_per_call=MAX_STEPS_PER_CALL + 5,
        )
    assert stats["steps_per_call"] == MAX_STEPS_PER_CALL
    assert any("clamping" in r.message for r in caplog.records)


def test_pipeline_superstep_clip_norm_warns_fence_floor(caplog):
    """clip_norm > 0 keeps a per-step fence (the global norm couples
    stages host-side): documented honestly with a loud warning, never
    silently serialized."""
    import logging

    pipe = _pipe(chunk=4, clip=1.0)
    with caplog.at_level(logging.WARNING, logger="ff.trainer"):
        Trainer(pipe).fit(iterations=2, warmup=1, steps_per_call=2)
    assert any("one-fence-per-step" in r.message for r in caplog.records)


def test_pipeline_superstep_accum_refused():
    pipe = _pipe(chunk=2)
    with pytest.raises(ValueError, match="accum"):
        Trainer(pipe).fit(iterations=2, steps_per_call=2, accum_steps=2)


# -- CLI / app plumbing -------------------------------------------------------


def test_pipeline_chunk_cli():
    assert FFConfig.parse_args(["--pipeline-chunk", "4"]).pipeline_chunk == 4
    assert FFConfig.parse_args([]).pipeline_chunk == 1
    with pytest.raises(SystemExit):
        FFConfig.parse_args(["--pipeline-chunk", "0"])


def test_pipeline_chunk_app_end_to_end():
    """--pipeline --pipeline-chunk --steps-per-call through the shared
    app harness (the test_apps nmt --pipeline pattern)."""
    from flexflow_tpu.apps import nmt

    assert nmt.main([
        "-b", "16", "-i", "2", "--hidden", "8", "--vocab", "32",
        "--src-len", "4", "--tgt-len", "4", "--pipeline",
        "-ll:tpu", "8", "--microbatches", "2", "--pipeline-chunk", "2",
        "--steps-per-call", "2",
    ]) == 0
