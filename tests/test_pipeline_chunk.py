"""Chunked-scan pipeline dispatch + pipeline supersteps (ISSUE 3).

The invariants pinned here extend the superstep family
(``tests/test_superstep.py``) to the layer-wise runtime:

- **Chunk invariance** — ``chunk=c`` folds each stage's per-microbatch
  fwd/bwd programs into ONE jitted ``lax.scan`` over ``c`` stacked
  microbatches; loss AND param trajectories must be BIT-IDENTICAL
  across ``c`` (the scan carries the running gradient/metric sums, so
  accumulation order is microbatch order regardless of chunking).
- **Dispatch accounting** — ``last_schedule`` records one event per
  host program: ``2*S*ceil(m/c)`` per step, dependency-valid at chunk
  granularity.
- **Pipeline supersteps** — ``Trainer.fit(steps_per_call=k)`` on a
  PipelineExecutor dispatches k steps back-to-back under ONE
  ``jax.device_get`` fence; trajectories bit-identical to k=1.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.graph import FFModel
from flexflow_tpu.optim import SGDOptimizer
from flexflow_tpu.parallel.strategy import ParallelConfig, StrategyStore
from flexflow_tpu.runtime.pipeline import PipelineExecutor
from flexflow_tpu.runtime.trainer import Trainer


def _model(batch=16, dropout=0.0):
    ff = FFModel(FFConfig(batch_size=batch))
    x = ff.create_tensor((batch, 12), name="x")
    lbl = ff.create_tensor((batch,), dtype=jnp.int32, name="label")
    t = ff.dense(x, 16, activation="relu", name="enc0")
    t = ff.dense(t, 16, activation="relu", name="enc1")
    if dropout > 0.0:
        t = ff.dropout(t, rate=dropout, name="drop")
    t = ff.dense(t, 16, activation="relu", name="dec0")
    t = ff.dense(t, 4, activation=None, name="dec1")
    ff.softmax(t, lbl, name="softmax")
    return ff


def _store(nd=8, with_dropout=False):
    enc = tuple(range(nd // 2))
    dec = tuple(range(nd // 2, nd))
    store = StrategyStore(nd)
    for n in ("enc0", "enc1"):
        store.set(n, ParallelConfig(n=len(enc), device_ids=enc))
    names = ("drop",) if with_dropout else ()
    for n in names + ("dec0", "dec1", "softmax"):
        store.set(n, ParallelConfig(n=len(dec), device_ids=dec))
    return store


def _batches(n, batch=16, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {
            "x": rng.standard_normal((batch, 12)).astype(np.float32),
            "label": rng.integers(0, 4, size=(batch,)).astype(np.int32),
        }
        for _ in range(n)
    ]


def _pipe_fresh(microbatches=4, chunk=1, schedule="1f1b", clip=0.0,
                dropout=0.0):
    cfg = FFConfig(batch_size=16, clip_norm=clip)
    return PipelineExecutor(
        _model(dropout=dropout), _store(with_dropout=dropout > 0.0),
        config=cfg, optimizer=SGDOptimizer(lr=0.1, momentum=0.9),
        microbatches=microbatches, schedule=schedule, chunk=chunk,
    )


@functools.lru_cache(maxsize=None)
def _pipe(microbatches=4, chunk=1, schedule="1f1b", clip=0.0, dropout=0.0):
    """Executors are stateless between train_step calls (params are
    explicit), so tests sharing a config share its compiled stage
    programs — the suite runs on one core and compiles dominate."""
    return _pipe_fresh(microbatches, chunk, schedule, clip, dropout)


def _run(pipe, batches):
    params, opt_state, state = pipe.init(seed=0)
    losses = []
    for b in batches:
        params, opt_state, state, m = pipe.train_step(
            params, opt_state, state, pipe.shard_batch(b)
        )
        losses.append(np.asarray(jax.device_get(m["train_loss"])))
    return np.array(losses), jax.device_get(params)


def _assert_bit_identical(run_a, run_b, msg=""):
    losses_a, params_a = run_a
    losses_b, params_b = run_b
    np.testing.assert_array_equal(losses_a, losses_b, err_msg=msg)
    for a, b in zip(jax.tree.leaves(params_a), jax.tree.leaves(params_b)):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=msg
        )


# -- chunk invariance ---------------------------------------------------------


@pytest.mark.parametrize("chunk", [2, 4])
def test_chunked_bit_identical_to_event_loop(chunk):
    """c in {2, m}: trajectories bit-identical to the c=1 per-microbatch
    event loop (the acceptance-criterion invariant)."""
    batches = _batches(3)
    ref = _run(_pipe(chunk=1), batches)
    got = _run(_pipe(chunk=chunk), batches)
    _assert_bit_identical(ref, got, f"chunk={chunk}")


def test_chunked_nondivisible_tail():
    """m=4, c=3: chunks of 3+1 microbatches — the short tail chunk is
    its own compiled scan length and numerics stay bit-identical."""
    batches = _batches(2)
    ref = _run(_pipe(chunk=1), batches)
    got = _run(_pipe(chunk=3), batches)
    _assert_bit_identical(ref, got, "chunk=3 (non-divisible)")


def test_chunked_schedule_invariant():
    """Chunked numerics are also schedule-invariant (1f1b vs gpipe at
    chunk granularity)."""
    batches = _batches(2)
    _assert_bit_identical(
        _run(_pipe(chunk=2, schedule="1f1b"), batches),
        _run(_pipe(chunk=2, schedule="gpipe"), batches),
    )


def test_chunked_clip_norm_bit_identical():
    """The batched clip-norm fence (ONE device_get of all S squared
    norms) preserves global-norm clipping numerics across chunking."""
    batches = _batches(2, seed=3)
    ref = _run(_pipe(chunk=1, clip=0.5), batches)
    got = _run(_pipe(chunk=4, clip=0.5), batches)
    _assert_bit_identical(ref, got, "clip_norm chunked")
    # And the clip actually engaged (scale < 1 at lr-sized grads).
    noclip = _run(_pipe(chunk=4), batches)
    assert not np.array_equal(
        jax.tree.leaves(ref[1])[0], jax.tree.leaves(noclip[1])[0]
    )


def test_chunked_dropout_rng_chain():
    """The stacked-prestate remat threads the dropout RNG chain through
    the scan exactly as the per-microbatch loop does."""
    batches = _batches(2)
    ref = _run(_pipe(chunk=1, dropout=0.5), batches)
    got = _run(_pipe(chunk=2, dropout=0.5), batches)
    _assert_bit_identical(ref, got, "dropout chunked")


def test_chunked_skip_connection(rng):
    """A stage-0 output consumed by TWO later stages: stacked cotangent
    contributions sum on the producer's mesh per chunk."""
    batch = 8
    ff = FFModel(FFConfig(batch_size=batch))
    x = ff.create_tensor((batch, 12), name="x")
    lbl = ff.create_tensor((batch,), dtype=jnp.int32, name="label")
    t0 = ff.dense(x, 8, activation="relu", name="s0")
    t1 = ff.dense(t0, 8, activation="relu", name="s1")
    t2 = ff.concat([t0, t1], axis=1, name="s2cat")
    t3 = ff.dense(t2, 4, activation=None, name="s2fc")
    ff.softmax(t3, lbl, name="softmax")
    store = StrategyStore(6)
    store.set("s0", ParallelConfig(n=2, device_ids=(0, 1)))
    store.set("s1", ParallelConfig(n=2, device_ids=(2, 3)))
    for name in ("s2cat", "s2fc", "softmax"):
        store.set(name, ParallelConfig(n=2, device_ids=(4, 5)))
    batch_data = {
        "x": rng.standard_normal((batch, 12)).astype(np.float32),
        "label": rng.integers(0, 4, size=(batch,)).astype(np.int32),
    }

    def run(chunk):
        pipe = PipelineExecutor(
            ff, store, optimizer=SGDOptimizer(lr=0.1),
            microbatches=2, chunk=chunk,
        )
        p, o, s = pipe.init(seed=0)
        p2, _, _, m = pipe.train_step(p, o, s, pipe.shard_batch(batch_data))
        return np.array(jax.device_get(m["train_loss"])), jax.device_get(p2)

    _assert_bit_identical(run(1), run(2), "skip connection chunked")


# -- dispatch accounting ------------------------------------------------------


@pytest.mark.parametrize("chunk,n_units", [(1, 4), (2, 2), (3, 2), (4, 1)])
def test_chunk_cuts_programs_per_step(chunk, n_units):
    """last_schedule records one event per host program: 2*S*ceil(m/c),
    dependency-valid at chunk granularity."""
    pipe = _pipe(microbatches=4, chunk=chunk)
    params, opt_state, state = pipe.init(seed=0)
    pipe.train_step(params, opt_state, state,
                    pipe.shard_batch(_batches(1)[0]))
    S = len(pipe.stages)
    ev = pipe.last_schedule
    assert len(ev) == 2 * S * n_units, (chunk, ev)
    assert ev == pipe.build_schedule(S, n_units)
    pos = {e: i for i, e in enumerate(ev)}
    for kind, si, ci in ev:
        if kind == "F" and si > 0:
            assert pos[("F", si - 1, ci)] < pos[("F", si, ci)]
        if kind == "B":
            assert pos[("F", si, ci)] < pos[("B", si, ci)]
            if si < S - 1:
                assert pos[("B", si + 1, ci)] < pos[("B", si, ci)]


def test_chunk_clamped_to_microbatches(caplog):
    import logging

    with caplog.at_level(logging.WARNING, logger="ff.pipeline"):
        pipe = _pipe_fresh(microbatches=2, chunk=8)
    assert pipe.chunk == 2
    assert any("clamping" in r.message for r in caplog.records)
    with pytest.raises(ValueError, match="chunk"):
        _pipe_fresh(chunk=0)


# -- pipeline supersteps ------------------------------------------------------


def test_pipeline_superstep_bit_identical():
    """k pipeline steps under ONE fence: loss/param trajectories
    bit-identical to steps_per_call=1, for c=1 and c=m."""
    n_steps, k = 6, 3
    batches = _batches(n_steps + 1)  # +1 warmup

    def fit(steps_per_call, chunk):
        pipe = _pipe(chunk=chunk)
        tr = Trainer(pipe)
        stats = tr.fit(
            iterations=n_steps, warmup=1, steps_per_call=steps_per_call,
            batches=iter(batches), prefetch=0,
        )
        return stats, jax.device_get(tr.final[0])

    s1, p1 = fit(1, 1)
    sk, pk = fit(k, 1)
    skc, pkc = fit(k, 4)
    assert sk["steps_per_call"] == k and sk["supersteps"] == 2
    for got in (pk, pkc):
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pipeline_superstep_remainder_and_stats():
    """iterations not divisible by k: the tail superstep is shorter;
    stats account every step exactly once (no warmup rounding on the
    pipeline path — there is no k-sized compiled program)."""
    pipe = _pipe(chunk=4)
    stats = Trainer(pipe).fit(iterations=5, warmup=2, steps_per_call=2)
    assert stats["iterations"] == 5
    assert stats["steps_per_call"] == 2
    assert stats["supersteps"] == 3  # 2 + 2 + 1
    assert stats["samples_per_s"] > 0


def test_pipeline_superstep_clamps(caplog):
    import logging

    from flexflow_tpu.runtime.trainer import MAX_STEPS_PER_CALL

    pipe = _pipe(chunk=4)
    with caplog.at_level(logging.WARNING, logger="ff.trainer"):
        stats = Trainer(pipe).fit(
            iterations=2, warmup=0, steps_per_call=MAX_STEPS_PER_CALL + 5,
        )
    assert stats["steps_per_call"] == MAX_STEPS_PER_CALL
    assert any("clamping" in r.message for r in caplog.records)


def test_pipeline_superstep_clip_norm_warns_fence_floor(caplog):
    """clip_norm > 0 keeps a per-step fence (the global norm couples
    stages host-side): documented honestly with a loud warning, never
    silently serialized."""
    import logging

    pipe = _pipe(chunk=4, clip=1.0)
    with caplog.at_level(logging.WARNING, logger="ff.trainer"):
        Trainer(pipe).fit(iterations=2, warmup=1, steps_per_call=2)
    assert any("one-fence-per-step" in r.message for r in caplog.records)


def test_pipeline_superstep_accum_refused():
    pipe = _pipe(chunk=2)
    with pytest.raises(ValueError, match="accum"):
        Trainer(pipe).fit(iterations=2, steps_per_call=2, accum_steps=2)


# -- CLI / app plumbing -------------------------------------------------------


def test_pipeline_chunk_cli():
    assert FFConfig.parse_args(["--pipeline-chunk", "4"]).pipeline_chunk == 4
    assert FFConfig.parse_args([]).pipeline_chunk == 1
    with pytest.raises(SystemExit):
        FFConfig.parse_args(["--pipeline-chunk", "0"])


@pytest.mark.slow  # ~8s app e2e; tier1_smoke runs it unfiltered
def test_pipeline_chunk_app_end_to_end():
    """--pipeline --pipeline-chunk --steps-per-call through the shared
    app harness (the test_apps nmt --pipeline pattern)."""
    from flexflow_tpu.apps import nmt

    assert nmt.main([
        "-b", "16", "-i", "2", "--hidden", "8", "--vocab", "32",
        "--src-len", "4", "--tgt-len", "4", "--pipeline",
        "-ll:tpu", "8", "--microbatches", "2", "--pipeline-chunk", "2",
        "--steps-per-call", "2",
    ]) == 0


# -- compiled whole-step path (ISSUE 5) ---------------------------------------
#
# PipelineExecutor(compiled=True): the whole multi-stage step is ONE
# jitted program on the shared stage mesh.  The HOST-DRIVEN path above is
# the numerics oracle: loss AND param trajectories must be BIT-IDENTICAL
# for the same schedule, across stage counts, non-divisible m, dropout,
# nested n/c inside stages, skip connections, and clip-norm (which runs
# device-side here — no fence floor).


@functools.lru_cache(maxsize=None)
def _pipe_c(microbatches=4, clip=0.0, dropout=0.0, compiled=True,
            accum_steps=1):
    cfg = FFConfig(batch_size=16, clip_norm=clip)
    return PipelineExecutor(
        _model(dropout=dropout), _store(with_dropout=dropout > 0.0),
        config=cfg, optimizer=SGDOptimizer(lr=0.1, momentum=0.9),
        microbatches=microbatches, compiled=compiled,
        accum_steps=accum_steps,
    )


@pytest.mark.parametrize(
    "dropout,clip",
    [(0.0, 0.0), (0.5, 0.0), (0.0, 0.5)],
    ids=["plain", "dropout", "clip_norm"],
)
def test_compiled_bit_identical_to_host(dropout, clip):
    """The headline gate: one compiled program per step, trajectories
    bit-identical to the host-driven event loop — incl. the dropout RNG
    chain and the device-side hierarchical clip-norm (vs the host
    path's fenced combine)."""
    batches = _batches(3, seed=3 if clip else 0)
    ref = _run(_pipe(chunk=1, clip=clip, dropout=dropout), batches)
    got = _run(_pipe_c(clip=clip, dropout=dropout), batches)
    _assert_bit_identical(ref, got, f"compiled dropout={dropout} clip={clip}")


def _deep_model(batch=16):
    ff = FFModel(FFConfig(batch_size=batch))
    x = ff.create_tensor((batch, 12), name="x")
    lbl = ff.create_tensor((batch,), dtype=jnp.int32, name="label")
    t = x
    for i in range(4):
        t = ff.dense(t, 16, activation="relu", name=f"fc{i}")
    t = ff.dense(t, 4, activation=None, name="head")
    ff.softmax(t, lbl, name="softmax")
    return ff


def _s4_store():
    st = StrategyStore(8)
    groups = [(0, 1), (2, 3), (4, 5), (6, 7)]
    assign = [["fc0"], ["fc1"], ["fc2"], ["fc3", "head", "softmax"]]
    for g, ns in zip(groups, assign):
        for n in ns:
            st.set(n, ParallelConfig(n=2, device_ids=g))
    return st


def _nc_store():
    st = StrategyStore(8)
    for n in ("fc0", "fc1"):
        st.set(n, ParallelConfig(n=2, c=2, device_ids=(0, 1, 2, 3)))
    for n in ("fc2", "fc3", "head"):
        st.set(n, ParallelConfig(n=2, c=2, device_ids=(4, 5, 6, 7)))
    st.set("softmax", ParallelConfig(n=4, device_ids=(4, 5, 6, 7)))
    return st


@pytest.mark.parametrize(
    "store_fn,mb,batch",
    [(_s4_store, 4, 16), (_s4_store, 3, 24), (_nc_store, 4, 16)],
    ids=["S4_n2", "S4_odd_m", "S2_nested_n2c2"],
)
@pytest.mark.slow  # ~14s matrix; tier1_smoke runs it unfiltered
def test_compiled_parity_corners(store_fn, mb, batch):
    """S=4 stage chains, m=3 (non-divisible 1f1b fill), and nested
    n/c sharding inside stages (the Linear contraction pin,
    ops/linear.py) — all bit-identical to the host path."""
    ff = _deep_model(batch)
    batches = _batches(2, batch=batch)

    def go(compiled):
        pipe = PipelineExecutor(
            ff, store_fn(), config=FFConfig(batch_size=batch),
            optimizer=SGDOptimizer(lr=0.1, momentum=0.9),
            microbatches=mb, compiled=compiled,
        )
        return _run(pipe, batches)

    _assert_bit_identical(go(False), go(True),
                          f"{store_fn.__name__} m={mb}")


def test_compiled_skip_connection(rng):
    """A stage-0 output consumed by TWO later stages: in-trace cotangent
    summation order matches _collect_douts'."""
    batch = 8
    ff = FFModel(FFConfig(batch_size=batch))
    x = ff.create_tensor((batch, 12), name="x")
    lbl = ff.create_tensor((batch,), dtype=jnp.int32, name="label")
    t0 = ff.dense(x, 8, activation="relu", name="s0")
    t1 = ff.dense(t0, 8, activation="relu", name="s1")
    t2 = ff.concat([t0, t1], axis=1, name="s2cat")
    t3 = ff.dense(t2, 4, activation=None, name="s2fc")
    ff.softmax(t3, lbl, name="softmax")
    store = StrategyStore(6)
    store.set("s0", ParallelConfig(n=2, device_ids=(0, 1)))
    store.set("s1", ParallelConfig(n=2, device_ids=(2, 3)))
    for name in ("s2cat", "s2fc", "softmax"):
        store.set(name, ParallelConfig(n=2, device_ids=(4, 5)))
    batch_data = {
        "x": rng.standard_normal((batch, 12)).astype(np.float32),
        "label": rng.integers(0, 4, size=(batch,)).astype(np.int32),
    }

    def run(compiled):
        pipe = PipelineExecutor(
            ff, store, optimizer=SGDOptimizer(lr=0.1),
            microbatches=2, compiled=compiled,
        )
        p, o, s = pipe.init(seed=0)
        p2, _, _, m = pipe.train_step(p, o, s, pipe.shard_batch(batch_data))
        return np.array(jax.device_get(m["train_loss"])), jax.device_get(p2)

    _assert_bit_identical(run(False), run(True), "skip connection compiled")


def test_compiled_eval_parity():
    """Compiled eval (one program, in-trace stage-order combine) matches
    the host path's fenced host-side sum bit-for-bit."""
    b = _batches(1)[0]
    host, comp = _pipe(chunk=4), _pipe_c()
    p, o, s = host.init(seed=0)
    pc, oc, sc = comp.init(seed=0)
    loss_h, mets_h = host.eval_step(p, s, host.shard_batch(b))
    loss_c, mets_c = comp.eval_step(pc, sc, comp.shard_batch(b))
    assert loss_h == loss_c
    assert set(mets_h) == set(mets_c)
    for k in mets_h:
        np.testing.assert_array_equal(np.asarray(mets_h[k]),
                                      np.asarray(mets_c[k]))


def test_compiled_accum_lowering():
    """--accum-steps on a layer-wise strategy lowers onto the microbatch
    loop: accumulating a groups of m microbatches IS the pipeline over
    a*m microbatches, on both runtimes."""
    batches = _batches(2)
    ref = _run(_pipe(microbatches=4, chunk=1), batches)
    for compiled in (False, True):
        got = _run(_pipe_c(microbatches=2, accum_steps=2,
                           compiled=compiled), batches)
        _assert_bit_identical(ref, got, f"accum lowered compiled={compiled}")


def test_compiled_zero_opt_refused():
    """--zero-opt stays refused on layer-wise strategies, naming the
    per-submesh moment-sharding blocker."""
    from flexflow_tpu.runtime.pipeline import PlacementError

    cfg = FFConfig(batch_size=16, zero_sharded_optimizer=True)
    with pytest.raises(PlacementError, match="PER-SUBMESH"):
        PipelineExecutor(_model(), _store(), config=cfg, microbatches=4)


def test_trainer_accum_requires_construction_lowering():
    """Trainer.fit(accum_steps=a) on a pipeline must match the
    executor's construction-time lowering — mismatches raise instead of
    silently double-stacking."""
    from flexflow_tpu.runtime.trainer import Trainer as Tr

    pipe = _pipe_c(microbatches=2, accum_steps=2)
    with pytest.raises(ValueError, match="lowered at construction"):
        Tr(pipe).fit(iterations=1, warmup=0, accum_steps=4)
    stats = Tr(pipe).fit(iterations=2, warmup=1, accum_steps=2)
    assert stats["iterations"] == 2


# -- fused pipeline supersteps ------------------------------------------------


def test_compiled_superstep_mode_promoted():
    """StrategyStore.superstep_mode: layer-wise stays "amortized" on the
    host path and promotes to "fused" on the compiled path; the
    executors expose the same split via superstep_fused."""
    store = _store()
    assert store.superstep_mode() == "amortized"
    assert store.superstep_mode(compiled=True) == "fused"
    assert not store.superstep_capable()
    assert store.superstep_capable(compiled=True)
    assert not _pipe(chunk=4).superstep_fused
    assert _pipe_c().superstep_fused


def test_compiled_superstep_bit_identical_and_counters(tmp_path):
    """--steps-per-call k on the compiled path: ONE dispatch + ONE
    fence per k steps (telemetry fence/programs counters audit it) and
    trajectories bit-identical to the k=1 host-driven run.  Warmup is
    sized to whole supersteps so both runs apply the same updates."""
    import json

    from flexflow_tpu.runtime.telemetry import Telemetry

    k, iters, warmup = 3, 6, 3
    batches = _batches(warmup + iters)

    def fit(pipe, steps_per_call):
        tr = Trainer(pipe)
        with Telemetry(str(tmp_path / f"k{steps_per_call}")) as tel:
            stats = tr.fit(
                iterations=iters, warmup=warmup,
                steps_per_call=steps_per_call, batches=iter(batches),
                prefetch=0,
            )
        with open(tel.path) as f:
            events = [json.loads(line) for line in f]
        return stats, jax.device_get(tr.final[0]), events

    s1, p1, _ = fit(_pipe(chunk=1), 1)
    sk, pk, events = fit(_pipe_c(), k)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(pk)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Fused-path accounting: programs/step == 1/k, one superstep fence
    # per k steps, and the compiled_step event names the fusion.
    assert sk["telemetry"]["programs_per_step"] == round(1 / k, 4)
    ss = [e for e in events if e["ev"] == "superstep"]
    assert len(ss) == 2 and all(e["k"] == k and e["mode"] == "fused"
                                for e in ss)
    fences = [e for e in events if e["ev"] == "fence"
              and e["label"] == "superstep"]
    assert len(fences) == 2
    compiled_evs = [e for e in events if e["ev"] == "compiled_step"]
    assert any(e["k"] == k and e["S"] == 2 and e["m"] == 4
               for e in compiled_evs)
    # No clip fence, no per-step fence: the step is fence-free IR.
    assert not [e for e in events if e["ev"] == "fence"
                and e["label"] == "clip_norm"]


def test_compiled_superstep_clip_norm_fence_free(tmp_path):
    """clip_norm > 0 on the compiled path keeps the fused superstep:
    NO per-step fence (the host path's loudly-warned floor is gone) and
    numerics bit-identical to the host-driven clipped run."""
    import json

    from flexflow_tpu.runtime.telemetry import Telemetry

    k, iters = 2, 4
    batches = _batches(k + iters, seed=3)

    def fit(pipe, steps_per_call):
        tr = Trainer(pipe)
        with Telemetry(str(tmp_path / f"clip{steps_per_call}")) as tel:
            tr.fit(iterations=iters, warmup=k,
                   steps_per_call=steps_per_call, batches=iter(batches),
                   prefetch=0)
        with open(tel.path) as f:
            events = [json.loads(line) for line in f]
        return jax.device_get(tr.final[0]), events

    p1, ev1 = fit(_pipe(chunk=1, clip=0.5), 1)
    pk, evk = fit(_pipe_c(clip=0.5), k)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(pk)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert [e for e in ev1 if e["ev"] == "fence"
            and e["label"] == "clip_norm"]  # host floor, still there
    assert not [e for e in evk if e["ev"] == "fence"
                and e["label"] == "clip_norm"]  # compiled: gone


def test_compiled_train_step_program_accounting():
    """One host program covers the whole compiled step: last_schedule
    records the single compiled event (vs 2*S*ceil(m/c) host events)."""
    pipe = _pipe_c()
    p, o, s = pipe.init(seed=0)
    pipe.train_step(p, o, s, pipe.shard_batch(_batches(1)[0]))
    assert pipe.last_schedule == [("C", 0, 0)]


# -- loud fallback ------------------------------------------------------------


def _fallback_warns(store, caplog, **kwargs):
    import logging

    from flexflow_tpu.runtime.pipeline import make_executor

    with caplog.at_level(logging.WARNING, logger="ff.pipeline"):
        ex = make_executor(_model(), store, config=FFConfig(batch_size=16),
                           optimizer=SGDOptimizer(lr=0.1),
                           microbatches=4, compiled=True, **kwargs)
    assert isinstance(ex, PipelineExecutor) and not ex.compiled
    assert any("--pipeline-compiled unavailable" in r.message
               for r in caplog.records)


def test_compiled_fallback_unequal_stages(caplog):
    """Unequal stage sizes have no shared stage mesh: loud fallback
    to the host-driven pipeline (the numerics oracle supports it)."""
    store = StrategyStore(8)
    for n in ("enc0", "enc1"):
        store.set(n, ParallelConfig(n=2, device_ids=(0, 1)))
    for n in ("dec0", "dec1", "softmax"):
        store.set(n, ParallelConfig(n=6, device_ids=(2, 3, 4, 5, 6, 7)))
    _fallback_warns(store, caplog)


def test_compiled_fallback_unverified_degrees(caplog):
    """Spatial (h/w) degrees and c on non-Linear ops are unverified
    against the submesh numerics: loud fallback, not silent 1-ulp
    drift."""
    store = _store()
    store.set("enc0", ParallelConfig(n=2, h=2, device_ids=(0, 1, 2, 3)))
    _fallback_warns(store, caplog)

    store = _store(with_dropout=True)
    store.set("drop", ParallelConfig(
        n=2, c=2, device_ids=tuple(range(4, 8))))
    ff = _model(dropout=0.5)
    import logging

    from flexflow_tpu.runtime.pipeline import make_executor

    with caplog.at_level(logging.WARNING, logger="ff.pipeline"):
        ex = make_executor(ff, store, config=FFConfig(batch_size=16),
                           optimizer=SGDOptimizer(lr=0.1),
                           microbatches=4, compiled=True)
    assert isinstance(ex, PipelineExecutor) and not ex.compiled


@pytest.mark.slow  # ~6s app e2e; tier1_smoke runs it unfiltered
def test_compiled_cli_and_app_end_to_end():
    """--pipeline-compiled parses and drives the fused superstep path
    through the shared app harness."""
    assert FFConfig.parse_args(["--pipeline-compiled"]).pipeline_compiled
    assert not FFConfig.parse_args([]).pipeline_compiled

    from flexflow_tpu.apps import nmt

    assert nmt.main([
        "-b", "16", "-i", "4", "--hidden", "8", "--vocab", "32",
        "--src-len", "4", "--tgt-len", "4", "--pipeline",
        "-ll:tpu", "8", "--microbatches", "2", "--pipeline-compiled",
        "--steps-per-call", "2",
    ]) == 0
