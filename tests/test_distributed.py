"""Multi-host/DCN mesh planning, emulated on the 8-device CPU mesh
(2 granules x 4 devices — the reference's 2-node x 4-GPU simulator
topology, ``simulator.cc:32-33``)."""

import jax
import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.graph import FFModel
from flexflow_tpu.optim import SGDOptimizer
from flexflow_tpu.parallel.distributed import build_hybrid_mesh_plan
from flexflow_tpu.parallel.strategy import ParallelConfig, StrategyStore
from flexflow_tpu.runtime.executor import Executor


def test_dcn_axes_outermost():
    plan = build_hybrid_mesh_plan(num_granules=2)
    assert plan.axis_names == ("d0", "x0", "x1")
    assert plan.axis_sizes == (2, 2, 2)


def test_dp_lands_on_dcn_tp_on_ici():
    """n consumes the slow (DCN) axis first; c/s stay on ICI — the
    'collectives ride ICI' layout rule."""
    plan = build_hybrid_mesh_plan(num_granules=2)
    asg = plan.assign(ParallelConfig(n=2, c=2, s=2))
    assert asg["n"] == ("d0",)
    assert set(asg["c"]) | set(asg["s"]) <= {"x0", "x1"}
    # Larger DP spills from DCN into ICI, never the reverse.
    asg4 = plan.assign(ParallelConfig(n=4, c=2))
    assert "d0" in asg4["n"]
    assert asg4["c"][0].startswith("x")


def test_granule_grouping_is_process_major():
    devs = jax.devices()
    plan = build_hybrid_mesh_plan(num_granules=2, devices=devs)
    arr = np.asarray(plan.mesh.devices).reshape(2, 4)
    # Each granule is a contiguous block of jax.devices() order.
    assert [d.id for d in arr[0]] == [d.id for d in devs[:4]]
    assert [d.id for d in arr[1]] == [d.id for d in devs[4:]]


def test_hybrid_plan_trains_and_matches_single_device(rng):
    ff = FFModel(FFConfig(batch_size=8))
    x = ff.create_tensor((8, 16), name="x")
    lbl = ff.create_tensor((8,), dtype=np.int32, name="label")
    t = ff.dense(x, 32, activation="relu", name="fc1")
    t = ff.dense(t, 4, name="fc2")
    ff.softmax(t, lbl, name="softmax")
    batch = {
        "x": rng.standard_normal((8, 16)).astype(np.float32),
        "label": rng.integers(0, 4, size=(8,)).astype(np.int32),
    }
    opt = SGDOptimizer(lr=0.1, momentum=0.9)

    ex1 = Executor(ff, optimizer=opt, devices=jax.devices()[:1])
    params, opt_state, state = ex1.init(seed=0)
    p1, *_ = ex1.train_step(jax.tree.map(np.asarray, params),
                            jax.tree.map(np.asarray, opt_state), state, batch)

    plan = build_hybrid_mesh_plan(num_granules=2)
    store = StrategyStore(8, {"fc1": ParallelConfig(n=2, c=4)})
    exh = Executor(ff, strategy=store, mesh_plan=plan, optimizer=opt)
    ph, *_ = exh.train_step(jax.tree.map(np.asarray, params),
                            jax.tree.map(np.asarray, opt_state), state, batch)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        p1, ph,
    )


def test_initialize_single_process_noop_in_k8s(monkeypatch):
    """An ordinary k8s pod (KUBERNETES_SERVICE_HOST set, no JAX cluster)
    must degrade to the single-process no-op, not crash."""
    from flexflow_tpu.parallel.distributed import initialize

    monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.0.0.1")
    initialize()  # must not raise


def test_initialize_rejects_partial_config(monkeypatch):
    from flexflow_tpu.parallel.distributed import initialize

    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.setenv("JAX_PROCESS_ID", "1")
    import pytest as _pytest
    with _pytest.raises(ValueError, match="process_id"):
        initialize()


def test_initialize_env_arg_precedence(monkeypatch):
    """The fallback ladder: explicit args win over JAX_* env, env wins
    over nothing — captured at the jax.distributed boundary."""
    from flexflow_tpu.parallel.distributed import initialize

    calls = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "env-host:1111")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    monkeypatch.setenv("JAX_PROCESS_ID", "3")
    initialize()
    assert calls[-1] == {"coordinator_address": "env-host:1111",
                         "num_processes": 4, "process_id": 3}
    initialize(coordinator_address="arg-host:2222",
               num_processes=2, process_id=1)
    assert calls[-1] == {"coordinator_address": "arg-host:2222",
                         "num_processes": 2, "process_id": 1}


def test_initialize_autodetect_failure_degrades(monkeypatch):
    """Cluster markers present but jax auto-detection unavailable
    (ordinary Slurm/k8s job with no JAX cluster behind it) must
    degrade to the single-process no-op, not crash the run."""
    from flexflow_tpu.parallel.distributed import initialize

    def boom(**kw):
        raise RuntimeError("Could not find coordinator address")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
              "JAX_PROCESS_ID"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("SLURM_JOB_ID", "12345")
    initialize()  # must not raise


def test_granule_count_validated():
    """User-facing ValueError (not a bare assert, which vanishes under
    ``python -O``) for granule counts that don't divide the devices."""
    with pytest.raises(ValueError, match="granule"):
        build_hybrid_mesh_plan(num_granules=3)
    with pytest.raises(ValueError, match="granule"):
        build_hybrid_mesh_plan(num_granules=0)


def test_world_single_process():
    from flexflow_tpu.parallel.distributed import world

    assert world() == (0, 1)


def test_moe_expert_parallel_on_hybrid_mesh(rng):
    """Expert parallelism composes with the DCN-outer pod layout: dp
    rides the d0 (DCN) axis, the experts' c-shard stays on ICI axes,
    and numerics match the flat single-granule mesh."""
    from flexflow_tpu.models.transformer import (
        build_transformer_lm,
        transformer_strategy,
    )

    def run(plan):
        ff = build_transformer_lm(
            batch_size=4, seq_len=8, vocab_size=64, d_model=16,
            num_heads=2, num_layers=1, moe_experts=4,
            config=FFConfig(batch_size=4, seed=2),
        )
        store = transformer_strategy(8, num_layers=1, dp=2, tp=4, moe=True)
        ex = Executor(ff, strategy=store, optimizer=SGDOptimizer(lr=0.05),
                      mesh_plan=plan)
        params, opt_state, state = ex.init()
        r = np.random.default_rng(0)
        batch = ex.shard_batch({
            "tokens": r.integers(0, 64, size=(4, 8)).astype(np.int32),
            "label": r.integers(0, 64, size=(4, 8)).astype(np.int32),
        })
        params, opt_state, state, m = ex.train_step(
            params, opt_state, state, batch
        )
        jax.block_until_ready(m)
        return float(m["train_loss"])

    hybrid = run(build_hybrid_mesh_plan(num_granules=2))
    flat = run(build_hybrid_mesh_plan(num_granules=1))
    np.testing.assert_allclose(hybrid, flat, rtol=2e-4)
