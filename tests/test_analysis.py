"""fflint — the framework-invariant static analyzer (ANALYSIS.md).

Layer 1 (AST rules): every rule has a positive test (a planted
violation in a temp module is caught) and rides the repo-wide negative
(the current repo is clean — which also pins the repo clean forever).
Layer 2 (program audit): planted violations — a VJP-less pallas op on
the training path, a host callback inside a compiled-pipeline step,
an undonated "donated" program — are flagged; the clean audit over
every registered op and executor family is the acceptance run.
"""

import importlib
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu.analysis import lint
from flexflow_tpu.analysis import program_audit as pa
from flexflow_tpu.config import FFConfig
from flexflow_tpu.graph import FFModel
from flexflow_tpu.ops.base import Op
from flexflow_tpu.parallel.strategy import ParallelConfig, StrategyStore
from flexflow_tpu.runtime.executor import Executor


def _ids(violations):
    return sorted({v.rule for v in violations})


# ---------------------------------------------------------------------------
# Layer 1: AST rules — planted positives
# ---------------------------------------------------------------------------


class TestLintRules:
    def test_relay_cap_matches_runtime(self):
        from flexflow_tpu.runtime.trainer import MAX_STEPS_PER_CALL

        assert lint.RELAY_CAP == MAX_STEPS_PER_CALL

    def test_ff001_block_until_ready(self):
        src = "import jax\njax.block_until_ready(x)\n"
        assert "FF001" in _ids(lint.lint_source(src, "planted.py"))
        # Method form too.
        src = "y = f(x).block_until_ready()\n"
        assert "FF001" in _ids(lint.lint_source(src, "planted.py"))

    def test_ff001_from_import_alias_is_caught(self):
        """Review finding: `from jax import block_until_ready` + a
        bare-name call must not evade the rule."""
        src = (
            "from jax import block_until_ready\n"
            "block_until_ready(x)\n"
        )
        vs = lint.lint_source(src, "planted.py")
        assert "FF001" in _ids(vs)
        assert any(v.line == 2 for v in vs)

    def test_ff001_docstring_reference_is_not_a_violation(self):
        src = '"""block_until_ready is mentioned in prose."""\n'
        assert lint.lint_source(src, "planted.py") == []

    def test_ff001_skips_tests(self):
        src = "import jax\njax.block_until_ready(x)\n"
        assert "FF001" not in _ids(
            lint.lint_source(src, "tests/test_planted.py")
        )

    def test_ff002_named_tpu_lookup(self):
        src = 'import jax\nd = jax.devices("tpu")\n'
        assert "FF002" in _ids(lint.lint_source(src, "planted.py"))
        # Positional cpu lookup and argless stay clean.
        src = 'import jax\nd = jax.devices("cpu")\ne = jax.devices()\n'
        assert "FF002" not in _ids(lint.lint_source(src, "planted.py"))

    def test_ff003_host_impurity_in_jit(self):
        src = (
            "import time, jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x * time.time()\n"
        )
        assert "FF003" in _ids(lint.lint_source(src, "planted.py"))
        # Call form: jax.jit(g) marks g as traced.
        src = (
            "import numpy as np, jax\n"
            "def g(x):\n"
            "    return x + np.random.rand()\n"
            "h = jax.jit(g)\n"
        )
        assert "FF003" in _ids(lint.lint_source(src, "planted.py"))
        # jax.random inside jit is the sanctioned RNG.
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def f(key):\n"
            "    return jax.random.normal(key, (4,))\n"
        )
        assert "FF003" not in _ids(lint.lint_source(src, "planted.py"))
        # Host time OUTSIDE jit is fine (the trainer does it).
        src = "import time\ndef f():\n    return time.time()\n"
        assert "FF003" not in _ids(lint.lint_source(src, "planted.py"))

    def test_ff004_bench_stdout_contract(self):
        bad = 'print("progress: 5/10")\n'
        assert "FF004" in _ids(lint.lint_source(bad, "bench.py"))
        ok = (
            "import json, sys\n"
            "print(json.dumps(result))\n"          # THE one JSON line
            'print("note", file=sys.stderr)\n'     # routed
        )
        assert "FF004" not in _ids(lint.lint_source(ok, "bench.py"))
        # Same bare print outside bench.py is out of scope.
        assert "FF004" not in _ids(lint.lint_source(bad, "planted.py"))
        # Review finding: an explicit file=sys.stdout must not pass.
        sneaky = 'import sys\nprint("x", file=sys.stdout)\n'
        assert "FF004" in _ids(lint.lint_source(sneaky, "bench.py"))

    def test_ff005_pallas_confinement(self):
        src = (
            "from jax.experimental import pallas as pl\n"
            "y = pl.pallas_call(k, out_shape=s)(x)\n"
        )
        vs = _ids(lint.lint_source(src, "flexflow_tpu/ops/linear.py"))
        assert "FF005" in vs
        # The kernel library and the sanctioned probe tools are exempt.
        for exempt in lint.PALLAS_ALLOWLIST:
            assert "FF005" not in _ids(lint.lint_source(src, exempt))
        # Review finding: the repo's OWN wrapper library is the
        # sanctioned import surface — not a confinement violation.
        ok = (
            "from flexflow_tpu.ops.pallas_kernels import flash_decode\n"
            "from flexflow_tpu.ops import pallas_kernels as pk\n"
        )
        assert "FF005" not in _ids(
            lint.lint_source(ok, "flexflow_tpu/ops/attention.py")
        )

    def test_ff006_unclamped_superstep_k(self):
        bad = "fn = ex.build_superstep(k)\n"
        assert "FF006" in _ids(lint.lint_source(bad, "planted.py"))
        bad = "fn = sex.build_decode_superstep(steps)\n"
        assert "FF006" in _ids(lint.lint_source(bad, "planted.py"))
        # Literal at/under the cap is safe by inspection.
        ok = f"fn = ex.build_superstep({lint.RELAY_CAP})\n"
        assert "FF006" not in _ids(lint.lint_source(ok, "planted.py"))
        # Literal ABOVE the cap is not.
        bad = f"fn = ex.build_superstep({lint.RELAY_CAP + 1})\n"
        assert "FF006" in _ids(lint.lint_source(bad, "planted.py"))
        # A module that clamps through the relay-cap helper is clean.
        ok = (
            "from flexflow_tpu.runtime.trainer import relay_safe_steps\n"
            "k = relay_safe_steps(k)\n"
            "fn = ex.build_superstep(k)\n"
        )
        assert "FF006" not in _ids(lint.lint_source(ok, "planted.py"))

    def test_ff007_tool_subprocess_timeout(self):
        src = (
            "import subprocess\n"
            "subprocess.run([cmd], timeout=30)\n"
        )
        assert "FF007" in _ids(lint.lint_source(src, "tools/planted.py"))
        # Out of tools/: other rules own it (bench probes are
        # documented protocol).
        assert "FF007" not in _ids(lint.lint_source(src, "bench.py"))
        # No timeout: clean.
        ok = "import subprocess\nsubprocess.run([cmd])\n"
        assert "FF007" not in _ids(lint.lint_source(ok, "tools/planted.py"))
        # Review finding: a module alias must not evade the rule.
        aliased = (
            "import subprocess as sp\n"
            "sp.run([cmd], timeout=30)\n"
        )
        assert "FF007" in _ids(
            lint.lint_source(aliased, "tools/planted.py")
        )

    def test_ff008_unregistered_event_name(self):
        bad = 'tel.emit("made_up_event", x=1)\n'
        assert "FF008" in _ids(lint.lint_source(bad, "planted.py"))
        bad = '_telemetry.current().emit("nope")\n'
        assert "FF008" in _ids(lint.lint_source(bad, "planted.py"))
        # Registered names, dynamic names, unrelated emit APIs: clean.
        ok = 'tel.emit("fault", mode="raise", step=2)\n'
        assert "FF008" not in _ids(lint.lint_source(ok, "planted.py"))
        ok = "tel.emit(name, x=1)\n"
        assert "FF008" not in _ids(lint.lint_source(ok, "planted.py"))
        ok = 'signal_bus.emit("made_up_event")\n'
        assert "FF008" not in _ids(lint.lint_source(ok, "planted.py"))
        # The emitter module itself is the one sanctioned home.
        assert "FF008" not in _ids(lint.lint_source(
            bad, "flexflow_tpu/runtime/telemetry.py"
        ))
        # The catalog copy is dependency-free; tests/test_obs.py pins
        # it equal to obs.events.EVENT_CATALOG.
        assert "run_start" in lint.FF008_EVENT_NAMES

    def test_planted_violation_in_temp_module(self, tmp_path):
        """End-to-end through lint_paths: a temp module on disk."""
        mod = tmp_path / "planted.py"
        mod.write_text("import jax\njax.block_until_ready(x)\n")
        vs = lint.lint_paths([str(mod)], root=str(tmp_path))
        assert _ids(vs) == ["FF001"]
        assert vs[0].path == "planted.py"
        assert vs[0].line == 2


class TestSuppression:
    def test_inline_suppression_round_trip(self):
        bad = "import jax\njax.block_until_ready(x)\n"
        assert "FF001" in _ids(lint.lint_source(bad, "planted.py"))
        ok = (
            "import jax\n"
            "jax.block_until_ready(x)  # fflint: disable=FF001\n"
        )
        assert lint.lint_source(ok, "planted.py") == []
        # The WRONG id does not suppress.
        still_bad = (
            "import jax\n"
            "jax.block_until_ready(x)  # fflint: disable=FF002\n"
        )
        assert "FF001" in _ids(lint.lint_source(still_bad, "planted.py"))

    def test_file_level_suppression(self):
        src = (
            "# fflint: disable-file=FF001\n"
            "import jax\n"
            "jax.block_until_ready(x)\n"
            "jax.block_until_ready(y)\n"
        )
        assert lint.lint_source(src, "planted.py") == []

    def test_multi_id_suppression(self):
        src = (
            "import jax\n"
            'jax.block_until_ready(jax.devices("tpu"))'
            "  # fflint: disable=FF001,FF002\n"
        )
        assert lint.lint_source(src, "planted.py") == []


class TestRepoClean:
    def test_repo_is_lint_clean(self):
        """The negative test for every rule at once — and the gate
        that keeps the repo clean: a new violation anywhere fails
        here with its file:line."""
        vs = lint.lint_paths()
        assert vs == [], "\n" + lint.format_report(vs)

    def test_rule_catalog_is_documented(self):
        """Every rule carries a rationale naming its hazard, and
        ANALYSIS.md documents every rule id."""
        import os

        for rule in lint.RULES:
            assert rule.rationale, rule.id
        doc = open(os.path.join(lint.repo_root(), "ANALYSIS.md")).read()
        for rule in lint.RULES:
            assert rule.id in doc, f"{rule.id} missing from ANALYSIS.md"
        for rid in ("FFP000", "FFP001", "FFP002", "FFP003", "FFP004",
                    "FFH001"):
            assert rid in doc, f"{rid} missing from ANALYSIS.md"


# ---------------------------------------------------------------------------
# Layer 2: program audit — planted violations
# ---------------------------------------------------------------------------


def _tiny_cfg(b=8):
    cfg = FFConfig(batch_size=b)
    cfg.num_devices = 8
    return cfg


class _VjplessPallasOp(Op):
    """A pallas kernel with NO AD rule on the training path — the
    exact violation FFP001 exists to catch (interpret mode, CPU-safe;
    the primitive lands in the jaxpr either way)."""

    def __init__(self, name, x):
        super().__init__(name, [x])
        self._make_output(x.shape, x.dtype, x.dim_axes)

    def forward(self, params, xs, state, training):
        from jax.experimental import pallas as pl  # fflint: disable=FF005

        def kern(x_ref, o_ref):
            o_ref[...] = x_ref[...] * 2.0

        (x,) = xs
        y = pl.pallas_call(
            kern, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=True,
        )(x)  # fflint: disable=FF005
        return [y], state


class _CallbackOp(Op):
    """A host callback inside the op forward — the FFP002 violation
    (reintroduces the per-dispatch host round-trip)."""

    def __init__(self, name, x):
        super().__init__(name, [x])
        self._make_output(x.shape, x.dtype, x.dim_axes)

    def forward(self, params, xs, state, training):
        (x,) = xs
        jax.debug.print("x sum {}", jnp.sum(x))
        return [x * 1.0], state


def _model_with(op_cls, name="bad"):
    ff = FFModel(_tiny_cfg())
    x = ff.create_tensor((8, 8), name="x")
    lbl = ff.create_tensor((8, 8), name="label")
    t = ff.dense(x, 8, name="fc0")
    op = op_cls(name, t)
    ff.layers.append(op)
    ff.mse_loss(op.outputs[0], lbl, name="mse")
    return ff


class TestProgramAuditPlanted:
    def test_vjpless_pallas_on_training_path_is_flagged(self):
        ff = _model_with(_VjplessPallasOp)
        ex = Executor(ff)
        vs = pa.audit_executor(ex)
        assert any(v.rule == "FFP001" for v in vs), [str(v) for v in vs]
        # Attribution names the offending op.
        assert any(v.op == "bad" for v in vs if v.rule == "FFP001")

    def test_sparse_keys_exempts_the_kernel(self):
        """The sparse-protocol escape hatch: the same jaxpr is clean
        when the owning op declares sparse_keys (ops/base.py)."""
        ff = _model_with(_VjplessPallasOp)
        ex = Executor(ff)
        params, _opt, state = ex._abstract_init()
        batch = ex._abstract_batch()

        def fwd(p, s, b):
            return ex.forward(p, s, b, training=True)[0]

        jaxpr = jax.make_jaxpr(fwd)(params, state, batch)
        flagged = pa.ad_reachability_violations(
            jaxpr, "t", ["bad"], sparse_ok=[]
        )
        assert any(v.rule == "FFP001" for v in flagged)
        exempt = pa.ad_reachability_violations(
            jaxpr, "t", ["bad"], sparse_ok=["bad"]
        )
        assert exempt == []
        # Serving programs are exempt wholesale (forward-only).
        assert pa.ad_reachability_violations(
            jaxpr, "t", ["bad"], serving=True
        ) == []

    def test_custom_vjp_wrapped_pallas_is_sanctioned(self):
        """The flash-attention pattern: pallas under custom_vjp."""
        from jax.experimental import pallas as pl  # fflint: disable=FF005

        def kern(x_ref, o_ref):
            o_ref[...] = x_ref[...] * 2.0

        def raw(x):
            return pl.pallas_call(
                kern, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                interpret=True,
            )(x)  # fflint: disable=FF005

        @jax.custom_vjp
        def wrapped(x):
            return raw(x)

        wrapped.defvjp(lambda x: (raw(x), None), lambda _, g: (2.0 * g,))
        jaxpr = jax.make_jaxpr(wrapped)(jnp.ones((8, 8)))
        assert pa.ad_reachability_violations(jaxpr, "t") == []

    def test_host_callback_in_compiled_step_is_flagged(self):
        """A host callback planted inside a COMPILED pipeline step."""
        from flexflow_tpu.runtime.pipeline import PipelineExecutor

        ff = FFModel(_tiny_cfg(16))
        x = ff.create_tensor((16, 8), name="x")
        lbl = ff.create_tensor((16, 8), name="label")
        t = ff.dense(x, 8, name="l0")
        op = _CallbackOp("cb", t)
        ff.layers.append(op)
        t2 = ff.dense(op.outputs[0], 8, name="l1")
        ff.mse_loss(t2, lbl, name="mse")
        store = StrategyStore(8)
        store.set("l0", ParallelConfig(n=4, device_ids=(0, 1, 2, 3)))
        store.set("l1", ParallelConfig(n=4, device_ids=(4, 5, 6, 7)))
        pipe = PipelineExecutor(ff, store, microbatches=2, compiled=True)
        vs = pa.audit_executor(pipe)
        assert any(v.rule == "FFP002" for v in vs), [str(v) for v in vs]

    def test_callback_in_full_mesh_train_step_is_flagged(self):
        ff = _model_with(_CallbackOp, name="cb")
        ex = Executor(ff)
        vs = pa.audit_executor(ex)
        assert any(v.rule == "FFP002" for v in vs), [str(v) for v in vs]

    def test_dropped_donation_is_flagged(self):
        """An undonated jit of the same step fails FFP003; the real
        (donated) train step passes."""
        ff = pa._conv_graph()
        ex = Executor(ff)
        params, opt, state = ex._abstract_init()
        batch = ex._abstract_batch()
        undonated = jax.jit(ex.build_train_step())
        vs = pa.donation_violations(
            undonated, "planted", (params, opt, state),
            params, opt, state, batch,
        )
        assert [v.rule for v in vs] == ["FFP003"]
        ok = pa.donation_violations(
            ex.train_step, "real", (params, opt, state),
            params, opt, state, batch,
        )
        assert ok == []

    def test_coverage_rule_fires_on_missing_op(self):
        partial = [("conv", pa._conv_graph())]
        vs = pa.coverage_violations(partial)
        assert vs and all(v.rule == "FFP000" for v in vs)
        missing = " ".join(v.message for v in vs)
        assert "LSTM" in missing and "MultiHeadAttention" in missing


class TestDispatchAccounting:
    def test_formulas_agree_with_schedule(self):
        """2*S*ceil(m/c) — the cost model, the schedule builder and
        the executor must all derive the same count."""
        assert pa._exec_config_programs_per_step(2, 4, 1, False) == 16
        assert pa._exec_config_programs_per_step(2, 4, 2, False) == 8
        assert pa._exec_config_programs_per_step(4, 8, 3, False) == 24
        assert pa._exec_config_programs_per_step(2, 4, 1, True) == 1.0
        assert pa._exec_config_programs_per_step(
            2, 4, 1, True, 8
        ) == pytest.approx(1 / 8)

    def test_live_pipeline_counters_match(self):
        """One real host-driven step and one compiled step on the
        virtual mesh must land exactly on the formulas (the telemetry
        cross-check of the full audit)."""
        assert pa._accounting_live_violations() == []


class TestAuditRepoClean:
    def test_fast_audit_is_clean(self):
        """The acceptance negative: every registered op and every
        executor family (full-mesh, pipeline host-driven, pipeline
        compiled, serving), trace-only layer."""
        vs = pa.audit_repo(fast=True)
        assert vs == [], "\n" + pa.format_report(vs)

    @pytest.mark.slow
    def test_full_audit_is_clean(self):
        """Compile-level layer: donation, HLO collectives, live
        telemetry accounting."""
        vs = pa.audit_repo(fast=False)
        assert vs == [], "\n" + pa.format_report(vs)

    def test_summary_line(self):
        assert pa.summary_line([]) == "audit: clean"
        v = pa.ProgramViolation("FFP001", "p", "m")
        assert "FFP001" in pa.summary_line([v])


# ---------------------------------------------------------------------------
# Migration: one audit surface
# ---------------------------------------------------------------------------


class TestAuditMigration:
    def test_runtime_audit_retired_with_pointer(self):
        # The deprecation shim served its cycle; a stale import must
        # now fail LOUDLY, naming the relocated surface.
        sys.modules.pop("flexflow_tpu.runtime.audit", None)
        with pytest.raises(ImportError, match="analysis.hlo"):
            importlib.import_module("flexflow_tpu.runtime.audit")
        sys.modules.pop("flexflow_tpu.runtime.audit", None)

    def test_hlo_family_reachable_from_analysis(self):
        from flexflow_tpu.analysis.hlo import collective_stats

        stats = collective_stats(
            "%ag = f32[16,128]{1,0} all-gather(%x), dimensions={0}"
        )
        assert len(stats) == 1 and stats[0].opcode == "all-gather"


# ---------------------------------------------------------------------------
# CLI + dry-run wiring
# ---------------------------------------------------------------------------


class TestCli:
    def test_lint_only_cli_exits_zero(self, capsys):
        from flexflow_tpu.analysis.__main__ import main

        assert main(["--lint-only"]) == 0
        assert "fflint: clean" in capsys.readouterr().out

    def test_lint_only_cli_exits_nonzero_on_violation(self, tmp_path,
                                                      capsys):
        from flexflow_tpu.analysis.__main__ import main

        mod = tmp_path / "planted.py"
        mod.write_text("import jax\njax.block_until_ready(x)\n")
        assert main(["--lint-only", str(mod)]) == 1
        assert "FF001" in capsys.readouterr().out


class TestDryRunAudit:
    def test_training_dry_run_prints_audit_verdict(self, capsys):
        from flexflow_tpu.apps.common import _dry_run

        ff = pa._conv_graph()
        ex = Executor(ff)
        stats = _dry_run(ff, ex, None)
        out = capsys.readouterr().out
        assert "audit: clean" in out
        assert stats["audit_violations"] == 0

    def test_dry_run_audit_event_lands_in_telemetry(self, tmp_path,
                                                    capsys):
        import json

        from flexflow_tpu.apps.common import _dry_run
        from flexflow_tpu.runtime import telemetry as _telemetry

        ff = pa._conv_graph()
        ex = Executor(ff)
        with _telemetry.Telemetry(directory=str(tmp_path)) as tel:
            _dry_run(ff, ex, None)
            path = tel.path
        events = [json.loads(l) for l in open(path)]
        ev = [e for e in events if e["ev"] == "analysis"]
        assert len(ev) == 1 and ev[0]["clean"] is True
