"""Streaming data plane tests (DATA.md): out-of-core loaders,
determinism contracts, checkpointed cursors, starvation telemetry.

The load-bearing pins:
- StreamingLoader over the same arrays/seed with window >= dataset is
  BIT-IDENTICAL to ArrayDataLoader, across epoch wraps (the composed
  epoch-permutation contract).
- Per-host shards are disjoint and covering.
- A mid-epoch checkpoint of the loader cursor+rng restores
  bit-identically through CheckpointManager's ``loader`` item — even
  into a fresh loader built with a different constructor seed.
- The chaos ``loader_fault`` scenario: a reader-thread OSError
  surfaces at next(), ResilientTrainer rolls back, rewinds the stream,
  and the recovered trajectory is bit-identical.
- ``input_wait`` telemetry accounting reconciles exactly: the summary
  total equals the sum of the emitted events' wall_s.
"""

import json
import os
import time

import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.data.loader import (
    ArrayDataLoader,
    DeviceMemoryError,
    DeviceResidentLoader,
    PrefetchLoader,
)
from flexflow_tpu.data.stream import (
    ArrayStreamSource,
    StreamingLoader,
    StreamReaderError,
    SyntheticStreamSource,
    ThrottledSource,
    loader_state_template,
    shard_for_host,
)
from flexflow_tpu.graph import FFModel
from flexflow_tpu.optim import SGDOptimizer
from flexflow_tpu.runtime.executor import Executor
from flexflow_tpu.runtime.telemetry import Telemetry
from flexflow_tpu.runtime.trainer import Trainer


def _arrays(rows=64, width=6, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "x": rng.standard_normal((rows, width)).astype(np.float32),
        "label": rng.integers(0, 4, size=(rows,)).astype(np.int32),
    }


def _mlp_executor(batch=8, width=6):
    ff = FFModel(FFConfig(batch_size=batch))
    x = ff.create_tensor((batch, width), name="x")
    lbl = ff.create_tensor((batch,), dtype=np.int32, name="label")
    t = ff.dense(x, 16, activation="relu", name="fc1")
    t = ff.dense(t, 4, name="fc2")
    ff.softmax(t, lbl, name="softmax")
    return Executor(ff, optimizer=SGDOptimizer(lr=0.1))


# -- determinism contracts -------------------------------------------------


def test_streaming_bit_identical_to_array_loader_across_wraps():
    """Window >= dataset: the streaming loader IS ArrayDataLoader,
    bit-for-bit, including the composed reshuffle at every epoch wrap
    (3 epochs here)."""
    arrays = _arrays()
    ref = ArrayDataLoader(arrays, batch_size=8, shuffle=True, seed=5)
    sl = StreamingLoader(ArrayStreamSource(arrays), 8, shuffle=True, seed=5)
    try:
        for i in range(24):  # 64 rows / batch 8 -> 8 steps/epoch
            want, got = ref.next_batch(), next(sl)
            assert sorted(want) == sorted(got)
            for k in want:
                np.testing.assert_array_equal(want[k], got[k], err_msg=f"batch {i} key {k}")
    finally:
        sl.close()


def test_streaming_unshuffled_and_windowed_cover_every_row():
    arrays = {"a": np.arange(40).reshape(40, 1).astype(np.float32)}
    for window in (0, 10):
        sl = StreamingLoader(ArrayStreamSource(arrays), 8, shuffle=True,
                             seed=1, shuffle_window=window)
        try:
            seen = np.concatenate([next(sl)["a"][:, 0] for _ in range(5)])
        finally:
            sl.close()
        assert sorted(seen.tolist()) == list(range(40))


def test_windowed_shuffle_stays_within_windows():
    """W < shard: shuffling is bounded to the window — row i can only
    appear inside its own window's span (the out-of-core contract)."""
    arrays = {"a": np.arange(32).reshape(32, 1).astype(np.float32)}
    sl = StreamingLoader(ArrayStreamSource(arrays), 8, shuffle=True,
                         seed=2, shuffle_window=8)
    try:
        for w in range(4):
            batch = next(sl)["a"][:, 0]
            assert sorted(batch.tolist()) == list(range(8 * w, 8 * w + 8))
    finally:
        sl.close()


def test_shard_disjointness():
    n = 67
    spans = [shard_for_host(n, h, 4) for h in range(4)]
    rows = [set(range(lo, hi)) for lo, hi in spans]
    assert all(len(r) == 67 // 4 for r in rows)
    for i in range(4):
        for j in range(i + 1, 4):
            assert not rows[i] & rows[j]
    arrays = {"a": np.arange(n).reshape(n, 1).astype(np.float32)}
    served = []
    for h in range(4):
        sl = StreamingLoader(ArrayStreamSource(arrays), 4, shuffle=True,
                             seed=9, host_id=h, num_hosts=4)
        try:
            served.append({int(v) for _ in range(4)
                           for v in next(sl)["a"][:, 0]})
        finally:
            sl.close()
    for i in range(4):
        assert served[i] <= rows[i]
        for j in range(i + 1, 4):
            assert not served[i] & served[j]


def test_synthetic_source_chunk_invariant():
    src = SyntheticStreamSource(
        {"x": ((3,), np.float32), "ids": ((2,), np.int32)},
        num_samples=50, seed=4, int_high={"ids": 10}, block=8)
    whole = src.read(0, 50)
    parts = [src.read(0, 13), src.read(13, 37), src.read(37, 50)]
    for k in whole:
        np.testing.assert_array_equal(
            whole[k], np.concatenate([p[k] for p in parts]))
    assert whole["ids"].max() < 10
    assert whole["x"].dtype == np.float32


# -- checkpointed cursor ---------------------------------------------------


@pytest.mark.parametrize("window", [0, 16], ids=["composed", "windowed"])
def test_checkpoint_roundtrip_midepoch(tmp_path, window):
    """CheckpointManager carries the loader cursor+rng as a ``loader``
    item; restoring into a FRESH loader (different constructor seed —
    the restored state must win) replays bit-identically mid-epoch."""
    from flexflow_tpu.runtime.checkpoint import CheckpointManager

    arrays = _arrays()
    sl = StreamingLoader(ArrayStreamSource(arrays), 8, shuffle=True,
                         seed=5, shuffle_window=window)
    params = {"w": np.zeros(2, np.float32)}
    try:
        for _ in range(11):  # mid-epoch-2 (8 steps/epoch)
            next(sl)
        with CheckpointManager(str(tmp_path)) as ck:
            ck.save(11, params, None, {}, loader=sl.state_dict())
        want = [next(sl) for _ in range(8)]
    finally:
        sl.close()

    fresh = StreamingLoader(ArrayStreamSource(arrays), 8, shuffle=True,
                            seed=777, shuffle_window=window)
    try:
        with CheckpointManager(str(tmp_path)) as ck:
            step, _, _, _, ls = ck.restore(
                templates=(params, None, {}),
                loader_template=loader_state_template())
        assert step == 11 and ls is not None
        fresh.load_state_dict(ls)
        for i, w in enumerate(want):
            got = next(fresh)
            for k in w:
                np.testing.assert_array_equal(w[k], got[k],
                                              err_msg=f"batch {i} key {k}")
    finally:
        fresh.close()


def test_checkpoint_without_loader_item_restores_none(tmp_path):
    """Pre-streaming checkpoints restore with loader=None (backward
    compatible in both directions)."""
    from flexflow_tpu.runtime.checkpoint import CheckpointManager

    params = {"w": np.ones(2, np.float32)}
    with CheckpointManager(str(tmp_path)) as ck:
        ck.save(3, params, None, {})
        step, p, _, _, ls = ck.restore(
            templates=(params, None, {}),
            loader_template=loader_state_template())
        assert step == 3 and ls is None
        # And the 4-tuple API is untouched.
        step4 = ck.restore(templates=(params, None, {}))
        assert len(step4) == 4


# -- resilience / chaos ----------------------------------------------------


@pytest.mark.slow
def test_chaos_loader_fault(tmp_path):
    """The full chaos scenario: reader-thread OSError surfaces at
    next(), ResilientTrainer restores the checkpoint + loader item,
    rewinds the stream, and recovers bit-identically."""
    from flexflow_tpu.runtime.chaos import run_matrix

    results = run_matrix(str(tmp_path), names=["loader_fault"])
    assert results, "loader_fault scenario missing from the matrix"
    ok, name, detail = results[0]
    assert ok, detail


def test_reader_error_surfaces_at_next():
    """Recoverable reader exceptions (OSError/RuntimeError) surface
    as-is; anything else is wrapped in StreamReaderError."""

    class Boom(ArrayStreamSource):
        def __init__(self, arrays, exc):
            super().__init__(arrays)
            self._exc = exc

        def read(self, start, stop):
            raise self._exc

    arrays = _arrays(rows=16)
    sl = StreamingLoader(Boom(arrays, OSError("disk gone")), 8, seed=0)
    with pytest.raises(OSError, match="disk gone"):
        next(sl)
    sl2 = StreamingLoader(Boom(arrays, KeyError("k")), 8, seed=0)
    with pytest.raises(StreamReaderError, match="reader thread failed"):
        next(sl2)


# -- starvation telemetry --------------------------------------------------


def test_input_wait_accounting_matches_events(tmp_path):
    """The folded input-wait stats reconcile EXACTLY with the emitted
    input_wait events: total == sum(event wall_s), count == #events,
    and the queue-depth gauges carry both edges (reader + h2d)."""
    arrays = _arrays(rows=96, width=6)
    ex = _mlp_executor(batch=8, width=6)
    throttled = ThrottledSource(ArrayStreamSource(arrays), per_row_s=2e-4)
    sl = StreamingLoader(throttled, 8, shuffle=True, seed=1,
                         shuffle_window=16)
    batches = PrefetchLoader(iter(sl), ex.shard_batch)
    with Telemetry(str(tmp_path)) as tel:
        stats = Trainer(ex).fit(iterations=8, batches=batches, warmup=1)
        path = tel.path
    batches.close()
    sl.close()

    summary = stats["telemetry"]
    assert summary["input_waits"] == 8  # steady-state steps only
    events = []
    with open(path) as f:
        for line in f:
            ev = json.loads(line)
            if ev.get("ev") == "input_wait":
                events.append(ev)
    assert len(events) == 8
    total = round(sum(ev["wall_s"] for ev in events), 6)
    assert summary["input_wait_s_total"] == pytest.approx(total, abs=1e-9)
    assert summary["input_wait_ms_p95"] >= summary["input_wait_ms_p50"] >= 0
    assert {"h2d", "reader"} <= set(events[0])


def test_telemetry_off_stats_unchanged():
    """Streaming with telemetry OFF: zero added keys, zero events —
    the off path stays the pinned 5-key stats dict."""
    arrays = _arrays(rows=64)
    ex = _mlp_executor()
    sl = StreamingLoader(ArrayStreamSource(arrays), 8, shuffle=True, seed=1)
    batches = PrefetchLoader(iter(sl), ex.shard_batch)
    stats = Trainer(ex).fit(iterations=4, batches=batches, warmup=1)
    batches.close()
    sl.close()
    assert sorted(stats) == [
        "batch_size", "elapsed_s", "iterations", "loss", "samples_per_s"]


def test_queue_depth_gauges_nest():
    arrays = _arrays(rows=32)
    ex = _mlp_executor()
    sl = StreamingLoader(ArrayStreamSource(arrays), 8, seed=0)
    pf = PrefetchLoader(iter(sl), ex.shard_batch)
    try:
        depths = pf.queue_depths()
        assert set(depths) == {"h2d", "reader"}
        assert all(isinstance(v, int) for v in depths.values())
    finally:
        pf.close()
        sl.close()


# -- end-to-end: DLRM trajectory ------------------------------------------


@pytest.mark.slow
def test_dlrm_streaming_loss_bit_identical():
    """The acceptance pin: DLRM trained from the streaming tier
    (window >= dataset, same seed) produces a final loss bit-identical
    to the ArrayDataLoader path — identical batch streams + identical
    init means identical trajectory."""
    from flexflow_tpu.data import make_dlrm_arrays
    from flexflow_tpu.models import DLRMConfig, build_dlrm, dlrm_strategy

    cfg = DLRMConfig(sparse_feature_size=4, embedding_size=[32] * 4,
                     mlp_bot=[8, 4], mlp_top=[4 + 4 * 4, 8, 1])
    arrays = make_dlrm_arrays(cfg, num_samples=64)

    def run(streaming):
        ff = build_dlrm(batch_size=8, dlrm=cfg)
        ex = Executor(ff, strategy=dlrm_strategy(8, cfg))
        if streaming:
            sl = StreamingLoader(ArrayStreamSource(arrays), 8,
                                 shuffle=True, seed=5)
            src = iter(sl)
        else:
            sl = None
            src = iter(ArrayDataLoader(arrays, 8, shuffle=True, seed=5))
        batches = PrefetchLoader(src, ex.shard_batch)
        try:
            return Trainer(ex).fit(iterations=12, batches=batches,
                                   warmup=0)["loss"]
        finally:
            batches.close()
            if sl is not None:
                sl.close()

    a, b = run(streaming=False), run(streaming=True)
    assert a == b  # bit-identical, not approx


# -- satellites ------------------------------------------------------------


def test_criteo_chunked_reader_and_stream_source(tmp_path):
    import h5py

    from flexflow_tpu.data.criteo import (
        CriteoStreamSource,
        load_criteo_h5,
        make_dlrm_arrays,
    )
    from flexflow_tpu.models import DLRMConfig

    path = str(tmp_path / "c.h5")
    rng = np.random.default_rng(0)
    with h5py.File(path, "w") as f:
        f.create_dataset("X_int",
                         data=rng.standard_normal((20, 4)).astype(np.float32))
        f.create_dataset("X_cat", data=rng.integers(0, 16, size=(20, 3)))
        f.create_dataset("y",
                         data=rng.integers(0, 2, size=20).astype(np.float32))

    # Chunked load == one-shot load; max_samples stops at the cut.
    whole = load_criteo_h5(path)
    chunked = load_criteo_h5(path, chunk_rows=7)
    for k in whole:
        np.testing.assert_array_equal(whole[k], chunked[k])
    cut = load_criteo_h5(path, max_samples=10, chunk_rows=4)
    for k in whole:
        np.testing.assert_array_equal(whole[k][:10], cut[k])

    dlrm = DLRMConfig(sparse_feature_size=2, embedding_size=[16, 16, 16],
                      mlp_bot=[4, 2], mlp_top=[2 + 3 * 2, 4, 1])
    ref = make_dlrm_arrays(dlrm, num_samples=20, path=path)
    src = CriteoStreamSource(path, dlrm)
    assert src.num_samples == 20
    got = src.read(5, 17)
    for k in ref:
        np.testing.assert_array_equal(ref[k][5:17], got[k], err_msg=k)
    src.close()


def test_device_resident_loader_memory_estimate(monkeypatch):
    arrays = _arrays(rows=64)
    staged = sum(v.nbytes for v in arrays.values())
    ex = _mlp_executor()
    monkeypatch.setenv("FF_DEVICE_MEM_BYTES", str(staged // 2))
    with pytest.raises(DeviceMemoryError, match="--stream-dataset"):
        DeviceResidentLoader(arrays, 8, ex, shuffle=True, seed=0)
    # A budget that fits stages normally.
    monkeypatch.setenv("FF_DEVICE_MEM_BYTES", str(staged * 100))
    dl = DeviceResidentLoader(arrays, 8, ex, shuffle=True, seed=0)
    assert next(iter(dl)) is not None


def test_prefetch_close_joins_bounded():
    """close() returns within its bounded timeout even when the worker
    is wedged inside a slow source read."""

    def slow():
        yield {"a": np.zeros((2, 2), np.float32)}
        time.sleep(30)
        yield {"a": np.zeros((2, 2), np.float32)}

    pf = PrefetchLoader(slow(), lambda b: b)
    next(pf)
    t0 = time.monotonic()
    pf.close(join_timeout_s=0.3)
    assert time.monotonic() - t0 < 5.0


def test_pipeline_accepts_lazy_sparse():
    """--lazy-sparse-opt on a layer-wise strategy constructs (the old
    loud refusal is gone): the sparse protocol is carried per-stage
    (tests/test_pipeline_sparse.py pins the numerics)."""
    from flexflow_tpu.parallel.strategy import ParallelConfig, StrategyStore
    from flexflow_tpu.runtime.pipeline import PipelineExecutor

    cfg = FFConfig(batch_size=8)
    cfg.lazy_sparse_optimizer = True
    ff = FFModel(cfg)
    x = ff.create_tensor((8, 6), name="x")
    lbl = ff.create_tensor((8,), dtype=np.int32, name="label")
    t = ff.dense(x, 16, activation="relu", name="fc1")
    t = ff.dense(t, 4, name="fc2")
    ff.softmax(t, lbl, name="softmax")
    store = StrategyStore(8, {
        "fc1": ParallelConfig(n=4, device_ids=tuple(range(4))),
        "fc2": ParallelConfig(n=4, device_ids=tuple(range(4, 8))),
        "softmax": ParallelConfig(n=4, device_ids=tuple(range(4, 8))),
    })
    pipe = PipelineExecutor(ff, store, microbatches=2)
    # Dense-only model: no stage carries sparse ops, and the step runs.
    assert all(not ops for ops in pipe._stage_sparse)


def test_trace_source_shapes_and_skew():
    from flexflow_tpu.data.trace import ProductionTraceSource

    src = ProductionTraceSource(200, dense_dim=4, vocab_sizes=[50, 50],
                                alpha=1.2, seed=0)
    specs = src.specs()
    assert set(specs) == {"dense_input", "label", "sparse_input"}
    got = src.read(0, 200)
    assert got["dense_input"].shape == (200, 4)
    assert got["label"].shape == (200, 1)
    assert got["sparse_input"].shape == (200, 2)
    ids = got["sparse_input"]
    assert ids.min() >= 0 and ids.max() < 50
    # Power-law skew: the most frequent id dominates a uniform draw.
    _, counts = np.unique(ids, return_counts=True)
    assert counts.max() > 3 * counts.mean()
    # Chunk invariance (block-deterministic generation).
    np.testing.assert_array_equal(
        got["sparse_input"][30:60], src.read(30, 60)["sparse_input"])
    with pytest.raises(ValueError, match="alpha"):
        ProductionTraceSource(10, dense_dim=2, vocab_sizes=[5], alpha=1.0)


def test_trace_hot_ids_deterministic():
    """The zipf hot set is a seed-keyed property of the trace, not of
    the reader: fresh instantiations, different read chunkings, and
    burst pacing all see the SAME id stream — so a sharded-embedding
    run replaying a ``--prod-trace`` (rollback, chaos ``loader_fault``)
    hits the same hot rows bit-for-bit."""
    from flexflow_tpu.data.trace import ProductionTraceSource

    mk = lambda **kw: ProductionTraceSource(
        120, dense_dim=2, vocab_sizes=[64, 64], alpha=1.3, seed=3, **kw)
    a = mk().read(0, 120)["sparse_input"]
    b = mk().read(0, 120)["sparse_input"]
    np.testing.assert_array_equal(a, b)
    # Chunked reads reassemble the identical stream.
    src = mk()
    chunked = np.concatenate(
        [src.read(i, i + 40)["sparse_input"] for i in (0, 40, 80)])
    np.testing.assert_array_equal(a, chunked)
    # Burst pacing stalls the reader, never perturbs content.
    np.testing.assert_array_equal(
        a, mk(burst_every=1, burst_s=0.001).read(0, 120)["sparse_input"])
    # And the hot head is actually hot (zipf, not uniform).
    _, counts = np.unique(a[:, 0], return_counts=True)
    assert counts.max() > 3 * counts.mean()


def test_stream_validation_errors():
    arrays = _arrays(rows=8)
    with pytest.raises(ValueError, match="batch_size"):
        StreamingLoader(ArrayStreamSource(arrays), 0)
    with pytest.raises(ValueError, match="shard"):
        StreamingLoader(ArrayStreamSource(arrays), 9)
    with pytest.raises(ValueError, match="shuffle_window"):
        StreamingLoader(ArrayStreamSource(arrays), 4, shuffle_window=-1)
