"""Strategy-invariance tests (SURVEY.md §4 plan (2)).

The reference's core promise is that any per-op strategy computes the
same function as single-device execution (it only ever asserts this
implicitly via partition-disjointness checks); here we assert it
numerically: train a small model under different strategies on the
8-device CPU mesh and require identical losses/params.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.graph import FFModel
from flexflow_tpu.optim import SGDOptimizer
from flexflow_tpu.parallel.strategy import ParallelConfig, StrategyStore
from flexflow_tpu.runtime.executor import Executor


def small_cnn(batch=8):
    ff = FFModel(FFConfig(batch_size=batch, seed=7))
    x = ff.create_tensor((batch, 8, 8, 4), name="x")
    lbl = ff.create_tensor((batch,), dtype=jnp.int32, name="lbl")
    t = ff.conv2d(x, 8, 3, 3, 1, 1, 1, 1, activation="relu", name="conv1")
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0, name="pool1")
    t = ff.flat(t, name="flat")
    t = ff.dense(t, 16, activation="relu", name="fc1")
    t = ff.dense(t, 4, activation=None, name="fc2")
    ff.softmax(t, lbl, name="softmax")
    return ff


def make_batch(ff, rng):
    return {
        "x": jnp.array(rng.standard_normal((8, 8, 8, 4)), jnp.float32),
        "lbl": jnp.array(rng.integers(0, 4, size=(8,)), jnp.int32),
    }


def train_losses(strategy_table, n_devices, steps=3):
    rng = np.random.default_rng(42)
    ff = small_cnn()
    store = StrategyStore(n_devices, strategy_table)
    ex = Executor(
        ff,
        strategy=store,
        optimizer=SGDOptimizer(lr=0.05, momentum=0.9),
        devices=jax.devices()[:n_devices],
    )
    params, opt_state, state = ex.init()
    losses = []
    for _ in range(steps):
        batch = ex.shard_batch(make_batch(ff, rng))
        params, opt_state, state, m = ex.train_step(params, opt_state, state, batch)
        losses.append(float(m["train_loss"]))
    return losses, jax.device_get(params)


def assert_same(run_a, run_b, rtol=2e-4):
    losses_a, params_a = run_a
    losses_b, params_b = run_b
    np.testing.assert_allclose(losses_a, losses_b, rtol=rtol, atol=1e-5)
    flat_a = jax.tree.leaves(params_a)
    flat_b = jax.tree.leaves(params_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(a, b, rtol=rtol, atol=1e-5)


def test_dp_matches_single_device():
    single = train_losses({}, 1)
    dp = train_losses({}, 8)  # fallback: full data parallelism
    assert_same(single, dp)


def test_tp_matches_single_device():
    tp = {
        "fc1": ParallelConfig(n=2, c=4),
        "fc2": ParallelConfig(n=2, c=2),
    }
    assert_same(train_losses({}, 1), train_losses(tp, 8))


def test_spatial_matches_single_device():
    sp = {
        "conv1": ParallelConfig(n=2, h=2, w=2),
        "pool1": ParallelConfig(n=2, h=2),
    }
    assert_same(train_losses({}, 1), train_losses(sp, 8))


def test_hybrid_matches_dp():
    hybrid = {
        "conv1": ParallelConfig(n=4, c=2),
        "fc1": ParallelConfig(c=8),
        "fc2": ParallelConfig(n=8),
    }
    assert_same(train_losses({}, 8), train_losses(hybrid, 8))


def test_losses_decrease():
    losses, _ = train_losses({}, 8, steps=10)
    assert losses[-1] < losses[0]
