"""Strategy-invariance tests (SURVEY.md §4 plan (2)).

The reference's core promise is that any per-op strategy computes the
same function as single-device execution (it only ever asserts this
implicitly via partition-disjointness checks); here we assert it
numerically: train a small model under different strategies on the
8-device CPU mesh and require identical losses/params.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.graph import FFModel
from flexflow_tpu.optim import SGDOptimizer
from flexflow_tpu.parallel.strategy import ParallelConfig, StrategyStore
from flexflow_tpu.runtime.executor import Executor


def small_cnn(batch=8):
    ff = FFModel(FFConfig(batch_size=batch, seed=7))
    x = ff.create_tensor((batch, 8, 8, 4), name="x")
    lbl = ff.create_tensor((batch,), dtype=jnp.int32, name="lbl")
    t = ff.conv2d(x, 8, 3, 3, 1, 1, 1, 1, activation="relu", name="conv1")
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0, name="pool1")
    t = ff.flat(t, name="flat")
    t = ff.dense(t, 16, activation="relu", name="fc1")
    t = ff.dense(t, 4, activation=None, name="fc2")
    ff.softmax(t, lbl, name="softmax")
    return ff


def make_batch(ff, rng):
    return {
        "x": jnp.array(rng.standard_normal((8, 8, 8, 4)), jnp.float32),
        "lbl": jnp.array(rng.integers(0, 4, size=(8,)), jnp.int32),
    }


def train_losses(strategy_table, n_devices, steps=3):
    rng = np.random.default_rng(42)
    ff = small_cnn()
    store = StrategyStore(n_devices, strategy_table)
    ex = Executor(
        ff,
        strategy=store,
        optimizer=SGDOptimizer(lr=0.05, momentum=0.9),
        devices=jax.devices()[:n_devices],
    )
    params, opt_state, state = ex.init()
    losses = []
    for _ in range(steps):
        batch = ex.shard_batch(make_batch(ff, rng))
        params, opt_state, state, m = ex.train_step(params, opt_state, state, batch)
        losses.append(float(m["train_loss"]))
    return losses, jax.device_get(params)


def assert_same(run_a, run_b, rtol=2e-4):
    losses_a, params_a = run_a
    losses_b, params_b = run_b
    np.testing.assert_allclose(losses_a, losses_b, rtol=rtol, atol=1e-5)
    flat_a = jax.tree.leaves(params_a)
    flat_b = jax.tree.leaves(params_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(a, b, rtol=rtol, atol=1e-5)


def test_dp_matches_single_device():
    single = train_losses({}, 1)
    dp = train_losses({}, 8)  # fallback: full data parallelism
    assert_same(single, dp)


def test_tp_matches_single_device():
    tp = {
        "fc1": ParallelConfig(n=2, c=4),
        "fc2": ParallelConfig(n=2, c=2),
    }
    assert_same(train_losses({}, 1), train_losses(tp, 8))


def test_spatial_matches_single_device():
    sp = {
        "conv1": ParallelConfig(n=2, h=2, w=2),
        "pool1": ParallelConfig(n=2, h=2),
    }
    assert_same(train_losses({}, 1), train_losses(sp, 8))


def test_hybrid_matches_dp():
    hybrid = {
        "conv1": ParallelConfig(n=4, c=2),
        "fc1": ParallelConfig(c=8),
        "fc2": ParallelConfig(n=8),
    }
    assert_same(train_losses({}, 8), train_losses(hybrid, 8))


def test_losses_decrease():
    losses, _ = train_losses({}, 8, steps=10)
    assert losses[-1] < losses[0]


# -- sharded embedding tables (ISSUE 20) --------------------------------------
#
# ``--shard-embeddings`` splits the table's vocab axis over the mesh
# c-axis (ops/embedding.py ``_sharded_gather``: owning shard resolves
# each id locally, psum combines — never a full-table all-gather).
# The DP≡strategy invariant must hold through the sharded gather, the
# sharded scatter-add backward, AND the lazy row-sparse optimizers.

VOCAB = 64


def emb_model(batch=8):
    ff = FFModel(FFConfig(batch_size=batch, seed=7, shard_embeddings=True))
    ids = ff.create_tensor((batch, 4), dtype=jnp.int32, name="ids")
    lbl = ff.create_tensor((batch,), dtype=jnp.int32, name="lbl")
    t = ff.embedding(ids, VOCAB, 8, aggr="sum", name="emb")
    t = ff.dense(t, 16, activation="relu", name="fc1")
    t = ff.dense(t, 4, activation=None, name="fc2")
    ff.softmax(t, lbl, name="softmax")
    return ff


def emb_train(strategy_table, n_devices, optimizer=None, steps=3):
    rng = np.random.default_rng(42)
    ff = emb_model()
    ex = Executor(
        ff,
        strategy=StrategyStore(n_devices, strategy_table),
        optimizer=optimizer or SGDOptimizer(lr=0.05, momentum=0.9),
        devices=jax.devices()[:n_devices],
    )
    params, opt_state, state = ex.init()
    losses = []
    for _ in range(steps):
        batch = ex.shard_batch({
            "ids": jnp.array(
                rng.integers(0, VOCAB, size=(8, 4)), jnp.int32),
            "lbl": jnp.array(rng.integers(0, 4, size=(8,)), jnp.int32),
        })
        params, opt_state, state, m = ex.train_step(
            params, opt_state, state, batch)
        losses.append(float(m["train_loss"]))
    return losses, jax.device_get(params)


@pytest.mark.parametrize("c", [2, 4])
def test_sharded_embedding_matches_dp(c):
    """c ∈ {2, 4}: the row-sharded table trains identically to full
    data parallelism (the acceptance-criterion invariant: sharded
    loss trajectory tracks the replicated DP run)."""
    sharded = {"emb": ParallelConfig(n=8 // c, c=c)}
    assert_same(emb_train({}, 8), emb_train(sharded, 8), rtol=1e-5)


def test_sharded_embedding_hybrid():
    """Hybrid n×c on the table composes with tensor parallelism on the
    dense tail."""
    hybrid = {
        "emb": ParallelConfig(n=2, c=2),
        "fc1": ParallelConfig(n=2, c=4),
        "fc2": ParallelConfig(n=8),
    }
    assert_same(emb_train({}, 8), emb_train(hybrid, 8))


def test_sharded_embedding_tight_vs_unsharded():
    """Same n-degree, only the table layout differs (c=4 sharded vs
    c=1 replicated): every other program is identical, so the
    trajectories agree to duplicate-id rounding (rtol 1e-6 — the
    sparse-suite precedent)."""
    a = emb_train({"emb": ParallelConfig(n=2, c=1)}, 8)
    b = emb_train({"emb": ParallelConfig(n=2, c=4)}, 8)
    np.testing.assert_allclose(a[0], b[0], rtol=1e-6)
    for x, y in zip(jax.tree.leaves(a[1]), jax.tree.leaves(b[1])):
        np.testing.assert_allclose(x, y, rtol=1e-6, atol=1e-7)


def test_lazy_adam_sharded_rows():
    """Lazy-sparse Adam over the c-sharded table: the row-sparse
    update (touched rows only) lands on the owning shards; the table
    trajectory matches the unsharded lazy run to ≤ a few ULP (the
    per-row Adam math is identical — only the scatter's shard-local
    RMW differs)."""
    from flexflow_tpu.optim import AdamOptimizer

    mk = lambda c: emb_train(
        {"emb": ParallelConfig(n=2, c=c)}, 8,
        optimizer=AdamOptimizer(lr=0.05, lazy_sparse=True),
    )
    a = mk(1)
    b = mk(4)
    np.testing.assert_allclose(a[0], b[0], rtol=1e-6)
    np.testing.assert_array_max_ulp(
        np.asarray(a[1]["emb"]["table"]),
        np.asarray(b[1]["emb"]["table"]).reshape(
            np.asarray(a[1]["emb"]["table"]).shape),
        maxulp=4,
    )
