"""Strategy-search subsystem: native simulator semantics + MCMC search.

The reference's equivalent is the offline simulator binary
(``scripts/simulator.cc``): event-driven list scheduling of shard +
comm tasks and Metropolis search.  The hand-computed schedule cases
here pin the scheduler's exact semantics (device timelines, channel
contention, rect-intersection comm volumes).
"""

import logging
import os

import numpy as np
import pytest

from flexflow_tpu.models.alexnet import build_alexnet
from flexflow_tpu.native import ffsim_search, ffsim_simulate
from flexflow_tpu.parallel.strategy import AXES, ParallelConfig, StrategyStore
from flexflow_tpu.search import search_strategy, simulate_strategy
from flexflow_tpu.search.problem import build_virtual_plan, shard_devices


def _problem(lines):
    return "\n".join(lines) + "\n"


class TestSimulatorSemantics:
    def test_single_op_compute_only(self):
        # One op, 2 shards of 5us each on distinct devices -> 5us.
        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 1",
            "op 0 1 solo",
            "cfg 2 1 1 1 1 5.0 0.0 0 1",
            "nedges 0",
        ])
        assert ffsim_simulate(p, [0]) == pytest.approx(5.0)

    def test_sync_cost_added_after_op(self):
        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 1",
            "op 0 1 solo",
            "cfg 2 1 1 1 1 5.0 3.0 0 1",
            "nedges 0",
        ])
        assert ffsim_simulate(p, [0]) == pytest.approx(8.0)

    def test_resharding_comm_hand_schedule(self):
        # op0 n-split rows of an (8,4) f32 tensor; op1 c-splits columns
        # and broadcasts rows.  Each cross-device transfer moves half a
        # source shard: 8 elems * 4B / bw 10 + 1us latency = 4.2us.
        # Comm starts when the producer shard finishes (5us); consumer
        # shards start at 9.2 and run 7us -> makespan 16.2.
        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 2",
            "op 0 1 producer",
            "cfg 2 1 1 1 1 5.0 0.0 0 1",
            "op 1 1 consumer",
            "cfg 1 2 1 1 1 7.0 0.0 0 1",
            "nedges 1",
            "edge 0 1 4 2 8 4 0 -1 -1 1",
        ])
        assert ffsim_simulate(p, [0, 0]) == pytest.approx(16.2)

    def test_same_device_transfer_is_free(self):
        # Same split on both ops, same placement: no comm, pure chain.
        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 2",
            "op 0 1 a",
            "cfg 2 1 1 1 1 5.0 0.0 0 1",
            "op 1 1 b",
            "cfg 2 1 1 1 1 7.0 0.0 0 1",
            "nedges 1",
            "edge 0 1 4 2 8 4 0 -1 0 -1",
        ])
        assert ffsim_simulate(p, [0, 0]) == pytest.approx(12.0)

    def test_search_picks_obvious_winner(self):
        # Config 1 halves the time with no comm downside; MCMC must
        # find it and report the config-0 start as the baseline.
        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 1",
            "op 0 2 solo",
            "cfg 1 1 1 1 1 10.0 0.0 0",
            "cfg 2 1 1 1 1 5.0 0.0 0 1",
            "nedges 0",
        ])
        res = ffsim_search(p, iters=50, seed=0, alpha=5.0)
        assert res["init_us"] == pytest.approx(10.0)
        assert res["best_us"] == pytest.approx(5.0)
        assert res["assign"] == [1]

    def test_bad_problem_raises(self):
        with pytest.raises(ValueError):
            ffsim_simulate("not a problem", [0])

    def test_zero_config_op_raises_not_crashes(self):
        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 1", "op 0 0 empty", "nedges 0",
        ])
        with pytest.raises(ValueError):
            ffsim_search(p, iters=10, seed=0, alpha=5.0)

    def test_bad_edge_axis_raises_not_crashes(self):
        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 2",
            "op 0 1 a", "cfg 2 1 1 1 1 5.0 0.0 0 1",
            "op 1 1 b", "cfg 2 1 1 1 1 5.0 0.0 0 1",
            "nedges 1",
            "edge 0 1 4 1 8 7 0",  # src axis 7 out of range
        ])
        with pytest.raises(ValueError):
            ffsim_simulate(p, [0, 0])


class TestShardDevices:
    def test_data_parallel_covers_all_devices(self):
        plan = build_virtual_plan(8)
        assert shard_devices(plan, ParallelConfig(n=8)) == list(range(8))

    def test_hybrid_covers_all_devices_once(self):
        plan = build_virtual_plan(8)
        devs = shard_devices(plan, ParallelConfig(n=2, c=4))
        assert sorted(devs) == list(range(8))

    def test_partial_split_replicates_on_first_coords(self):
        plan = build_virtual_plan(8)
        devs = shard_devices(plan, ParallelConfig(n=2))
        assert len(devs) == 2
        assert len(set(devs)) == 2

    def test_explicit_device_ids_win(self):
        plan = build_virtual_plan(8)
        pc = ParallelConfig(c=4, device_ids=(3, 1, 2, 0))
        assert shard_devices(plan, pc) == [3, 1, 2, 0]



def _run_one_train_step(ff, store, n_classes, image, n_devices=8):
    """One executor train step under a strategy; asserts finite loss."""
    import jax

    from flexflow_tpu.optim import SGDOptimizer
    from flexflow_tpu.runtime.pipeline import make_executor

    ex = make_executor(ff, store, optimizer=SGDOptimizer(lr=0.01),
                       devices=jax.devices()[:n_devices])
    params, opt_state, state = ex.init()
    rng = np.random.default_rng(0)
    batch = ex.shard_batch({
        "image": rng.standard_normal(image).astype(np.float32),
        "label": rng.integers(0, n_classes, size=(image[0],)).astype(np.int32),
    })
    params, opt_state, state, metrics = ex.train_step(
        params, opt_state, state, batch
    )
    jax.block_until_ready(metrics)
    assert np.isfinite(float(metrics["train_loss"]))


class TestEndToEndSearch:
    @pytest.fixture(scope="class")
    def alexnet(self):
        return build_alexnet(batch_size=64, image_size=229, num_classes=1000)

    def test_search_beats_or_matches_dp(self, alexnet):
        res = search_strategy(alexnet, num_devices=8, iters=3000, seed=0)
        assert res.best_time_us <= res.dp_time_us
        # AlexNet's FC gradient sync makes DP clearly sub-optimal — the
        # ICML'18 result the search must reproduce in simulation.
        assert res.speedup > 1.5
        assert set(res.assignment) == {op.name for op in alexnet.layers}
        for pc in res.assignment.values():
            assert pc.num_parts <= 8

    def test_store_roundtrip_and_simulate(self, alexnet, tmp_path):
        res = search_strategy(alexnet, num_devices=8, iters=2000, seed=1)
        path = tmp_path / "strategy.json"
        res.store.save(str(path))
        loaded = StrategyStore.load(str(path))
        t = simulate_strategy(alexnet, loaded, 8)
        assert t == pytest.approx(res.best_time_us, rel=1e-6)

    def test_dp_store_matches_reported_baseline(self, alexnet):
        res = search_strategy(alexnet, num_devices=8, iters=100, seed=0)
        # A store with no entries = the runtime's DP fallback; candidate
        # 0 of every op is the same config, so times must agree.
        dp_t = simulate_strategy(alexnet, StrategyStore.data_parallel(8), 8)
        assert dp_t == pytest.approx(res.dp_time_us, rel=1e-6)

    def test_simulate_strategy_measured_costs(self, alexnet):
        """simulate_strategy prices ops from a measured table when
        given one (the ffsim-calibration path, tools/calibrate_ffsim)."""
        flat = {op.name: 1000.0 for op in alexnet.layers}
        store = StrategyStore.data_parallel(8)
        t_meas = simulate_strategy(alexnet, store, 8, measured_costs=flat)
        t_roof = simulate_strategy(alexnet, store, 8)
        assert t_meas != pytest.approx(t_roof)
        # 1000 us/op fwd (x the fwd+bwd factor) across a sequential
        # graph: the makespan must scale with op count.
        assert t_meas > 1000.0 * len(alexnet.layers) / 8

    def test_measured_costs_override_roofline(self, alexnet):
        """Per-op measured times (runtime.profiler.measured_cost_table
        format) replace the roofline estimate and change the simulated
        baseline accordingly."""
        flat = {op.name: 1000.0 for op in alexnet.layers}
        res = search_strategy(
            alexnet, num_devices=8, iters=100, seed=0, measured_costs=flat
        )
        res2 = search_strategy(alexnet, num_devices=8, iters=100, seed=0)
        assert res.dp_time_us != pytest.approx(res2.dp_time_us)
        assert res.best_time_us <= res.dp_time_us

    @pytest.mark.slow  # ~3 min of live per-op microbenchmarks
    def test_cli_measured_mode(self, tmp_path, capsys):
        """``python -m flexflow_tpu.search --measured`` microbenches
        every op live (the reference's measured simulator inputs,
        ``scripts/cnn.h:204+``) and still emits a loadable strategy."""
        from flexflow_tpu.search.__main__ import main

        out = tmp_path / "strategy.json"
        assert main([
            "--model", "alexnet", "-b", "2", "--devices", "4",
            "--iters", "200", "--measured", "-o", str(out),
        ]) in (0, None)
        assert "measured 13 op costs" in capsys.readouterr().out
        loaded = StrategyStore.load(str(out))
        assert loaded.num_devices == 4

    def test_searched_strategy_runs_on_executor(self, alexnet):
        """The emitted table must be consumable by the runtime: compile
        and run one train step under the searched strategy on the
        8-device CPU mesh."""
        from flexflow_tpu.models.alexnet import build_alexnet as _b

        ff = _b(batch_size=8, image_size=67, num_classes=10)
        res = search_strategy(ff, num_devices=8, iters=500, seed=0)
        _run_one_train_step(ff, res.store, 10, (8, 67, 67, 3))

    @pytest.mark.slow  # ~78s Inception compile (targeted: test_search)
    def test_inception_op_parallel_strategy_runs(self):
        """BASELINE config #2: Inception-V3 blocks under a searched
        n/c/h/w operator-parallel strategy on 4 chips (virtual mesh).
        The searched table must beat or match simulated DP and run."""
        from flexflow_tpu.models import build_inception_v3

        ff = build_inception_v3(batch_size=4, image_size=75, num_classes=8)
        res = search_strategy(ff, num_devices=4, iters=300, seed=0)
        assert res.best_time_us <= res.dp_time_us * (1 + 1e-6)
        # At least one op got a non-pure-data-parallel config.
        assert any(
            pc.degree("c") > 1 or pc.degree("h") > 1 or pc.degree("w") > 1
            for pc in res.assignment.values()
        )
        _run_one_train_step(ff, res.store, 8, (4, 75, 75, 3), n_devices=4)

    def test_bad_edge_rank_raises_not_crashes(self):
        # nd = -1 previously hit vector::resize -> std::terminate.
        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 2",
            "op 0 1 a", "cfg 2 1 1 1 1 5.0 0.0 0 1",
            "op 1 1 b", "cfg 2 1 1 1 1 5.0 0.0 0 1",
            "nedges 1",
            "edge 0 1 4 -1",
        ])
        with pytest.raises(ValueError):
            ffsim_simulate(p, [0, 0])

    def test_oversized_counts_raise_not_allocate(self):
        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 2000000000",
        ])
        with pytest.raises(ValueError):
            ffsim_simulate(p, [0])

    def test_degree_exceeding_ndevices_raises(self):
        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 1", "op 0 1 a", "cfg 4 1 1 1 1 5.0 0.0 0 1 2 3",
            "nedges 0",
        ])
        with pytest.raises(ValueError):
            ffsim_simulate(p, [0])


class TestDeviceShiftedCandidates:
    def test_candidates_include_shifted_blocks(self):
        """Pure-n sub-mesh candidates exist on every aligned block, not
        just the mesh origin (the reference's per-table DLRM pinning
        freedom, dlrm_strategy.cc:11-19)."""
        from flexflow_tpu.config import FFConfig
        from flexflow_tpu.graph import FFModel
        from flexflow_tpu.search.problem import enumerate_candidates

        ff = FFModel(FFConfig(batch_size=8))
        x = ff.create_tensor((8, 16), name="x")
        ff.dense(x, 16, name="fc")
        plan = build_virtual_plan(4)
        cands = enumerate_candidates(ff.layers[0], plan)
        ids = {pc.device_ids for pc in cands if pc.device_ids is not None}
        assert (1,) in ids and (2,) in ids and (3,) in ids
        assert (2, 3) in ids

    def test_searched_placement_table_executes(self):
        """A searched table that mixes full-mesh and pinned ops (every
        op carrying explicit device_ids) must run via make_executor."""
        import jax

        from flexflow_tpu.config import FFConfig
        from flexflow_tpu.graph import FFModel
        from flexflow_tpu.optim import SGDOptimizer
        from flexflow_tpu.runtime.pipeline import make_executor

        ff = FFModel(FFConfig(batch_size=8))
        import jax.numpy as jnp

        ids_t = ff.create_tensor((8, 2), dtype=jnp.int32, name="ids")
        lbl = ff.create_tensor((8,), dtype=jnp.int32, name="label")
        e = ff.multi_embedding(ids_t, 2, 16, 4, name="tables")
        e = ff.reshape(e, (8, 8), name="r")
        t = ff.dense(e, 8, activation="relu", name="fc1")
        t = ff.dense(t, 4, name="fc2")
        ff.softmax(t, lbl, name="softmax")

        store = StrategyStore(4)
        # tables pinned off-origin, trunk on the full mesh.
        store.set("tables", ParallelConfig(device_ids=(2,)))
        for name in ("r", "fc1", "fc2", "softmax"):
            store.set(name, ParallelConfig(n=4, device_ids=(0, 1, 2, 3)))
        t_sim = simulate_strategy(ff, store, 4)
        assert np.isfinite(t_sim) and t_sim > 0
        ex = make_executor(ff, store, optimizer=SGDOptimizer(lr=0.1),
                           devices=jax.devices()[:4])
        params, opt_state, state = ex.init()
        rng = np.random.default_rng(0)
        batch = ex.shard_batch({
            "ids": rng.integers(0, 16, size=(8, 2)).astype(np.int32),
            "label": rng.integers(0, 4, size=(8,)).astype(np.int32),
        })
        params, opt_state, state, m = ex.train_step(
            params, opt_state, state, batch
        )
        assert np.isfinite(float(jax.device_get(m["train_loss"])))


class TestMeasuredDegrees:
    """Per-(op, degree) measured cost tables (the reference's
    ``computeTime[config]`` cache filled by live microbenchmarks per
    parallel degree, ``scripts/cnn.h:204-260``, ``simulator.cc:
    142-151``) replacing the whole-op / num_parts linear assumption."""

    def _model(self):
        import jax.numpy as jnp

        from flexflow_tpu.config import FFConfig
        from flexflow_tpu.graph import FFModel

        batch = 8
        ff = FFModel(FFConfig(batch_size=batch))
        x = ff.create_tensor((batch, 1024), name="x")
        lbl = ff.create_tensor((batch,), dtype=jnp.int32, name="label")
        t = ff.dense(x, 1024, activation="relu", name="fc")
        t = ff.dense(t, 16, name="head")
        ff.softmax(t, lbl, name="softmax")
        return ff

    def test_shard_local_shapes(self):
        from flexflow_tpu.runtime.profiler import _shard_shapes

        ff = self._model()
        fc = ff.layers[0]
        xs, ps, _ = _shard_shapes(fc, ParallelConfig(n=2, c=4))
        # Input: batch split by n, contracted feature dim kept FULL.
        assert xs == [(4, 1024)]
        # Kernel rows (out features, 'c') split 4-ways; bias likewise.
        assert ps["kernel"] == (256, 1024)
        assert ps["bias"] == (256,)

    def test_structural_cache_dedupes(self):
        """Identical shard geometries (same type/attrs/local shapes)
        are measured once — the reference's computeTime[] keyed by op
        hash + config (``simulator.cc:142-151``)."""
        from flexflow_tpu.runtime.profiler import measured_degree_table

        import jax.numpy as jnp

        from flexflow_tpu.config import FFConfig
        from flexflow_tpu.graph import FFModel

        calls = []

        def measure(op, pc, p, xs, s):
            calls.append((op.name, tuple(x.shape for x in xs)))
            return 10.0

        # Two structurally identical dense layers (the repeated-block
        # Inception case): the second one's candidates must all hit
        # the first one's cache entries.
        ff = FFModel(FFConfig(batch_size=8))
        x = ff.create_tensor((8, 64), name="x")
        lbl = ff.create_tensor((8,), dtype=jnp.int32, name="label")
        t = ff.dense(x, 64, activation="relu", name="fc1")
        t = ff.dense(t, 64, activation="relu", name="fc2")
        ff.softmax(t, lbl, name="softmax")
        table = measured_degree_table(ff, 8, measure=measure)
        assert set(table) == {"fc1", "fc2", "softmax"}
        assert table["fc1"] == table["fc2"]
        assert not any(name == "fc2" for name, _ in calls)
        assert all(us > 0 for v in table.values() for us in v.values())

    def test_measured_search_diverges_from_roofline(self):
        """The VERDICT-item acceptance: measured per-degree costs make
        the search pick a different (simulated-better-under-measure)
        strategy than the roofline on the same graph.  The injected
        measure models an MXU utilization floor: per-shard time scales
        with local rows but TP shards pay a fixed small-tile penalty —
        exactly the nonlinearity the old measured/parts linear scaling
        could not express."""
        from flexflow_tpu.runtime.profiler import measured_degree_table

        ff = self._model()
        roofline = search_strategy(ff, num_devices=8, iters=5000, seed=0)
        # Roofline: the big fc weight makes DP grad-sync dominant, so
        # the search tensor-parallelizes fc.
        assert roofline.assignment["fc"].c > 1

        def measure(op, pc, p, xs, s):
            return 10.0 * xs[0].shape[0] + 200.0 * (pc.degree("c") - 1)

        table = measured_degree_table(ff, 8, measure=measure)
        measured = search_strategy(
            ff, num_devices=8, iters=5000, seed=0, measured_costs=table
        )
        assert measured.assignment["fc"].c == 1
        assert measured.assignment["fc"] != roofline.assignment["fc"]

    def test_measured_bwd_asymmetry_changes_strategy(self):
        """VERDICT r4 acceptance: an op whose BACKWARD cost scales
        differently from its forward must steer the search away from
        the strategy the legacy fwd-only x3.0 assumption picks — the
        reason the reference measures ``t1+t2+t3`` per config instead
        of scaling forward (``scripts/cnn.h:252-277``)."""
        from flexflow_tpu.runtime.profiler import measured_degree_table

        ff = self._model()

        def fwd_only(op, pc, p, xs, s):
            # Legacy scalar entries: downstream applies x3.0.
            return 10.0 * xs[0].shape[0]

        def fwd_bwd(op, pc, p, xs, s):
            # Identical forward; backward pays a per-degree penalty
            # under c-splits (the conv-halo / embedding-scatter shape
            # of asymmetry) that no fwd-derived factor can express.
            fwd = 10.0 * xs[0].shape[0]
            return (fwd, 2.0 * fwd + 500.0 * (pc.degree("c") - 1))

        legacy = search_strategy(
            ff, num_devices=8, iters=5000, seed=0,
            measured_costs=measured_degree_table(ff, 8, measure=fwd_only),
        )
        measured = search_strategy(
            ff, num_devices=8, iters=5000, seed=0,
            measured_costs=measured_degree_table(ff, 8, measure=fwd_bwd),
        )
        # Same forward numbers; only the measured bwd leg differs —
        # the big fc flips from TP (grad-sync relief) to replicated.
        assert legacy.assignment["fc"].c > 1
        assert measured.assignment["fc"].c == 1
        assert measured.assignment["fc"] != legacy.assignment["fc"]

    def test_real_timing_smoke(self):
        """The real two-point fori_loop timer produces positive,
        finite per-degree times on the CPU backend for a tiny model
        and the search consumes them end to end."""
        import jax.numpy as jnp

        from flexflow_tpu.config import FFConfig
        from flexflow_tpu.graph import FFModel
        from flexflow_tpu.runtime.profiler import measured_degree_table

        ff = FFModel(FFConfig(batch_size=8))
        x = ff.create_tensor((8, 32), name="x")
        lbl = ff.create_tensor((8,), dtype=jnp.int32, name="label")
        t = ff.dense(x, 16, activation="relu", name="fc")
        ff.softmax(t, lbl, name="softmax")
        table = measured_degree_table(ff, 4, loops=(2, 6))
        assert table
        for v in table.values():
            for fwd_us, bwd_us in v.values():
                assert np.isfinite(fwd_us) and fwd_us > 0
                assert np.isfinite(bwd_us) and bwd_us >= 0
        res = search_strategy(
            ff, num_devices=4, iters=1000, seed=0, measured_costs=table
        )
        assert res.best_time_us > 0


class TestSearchTemperature:
    def test_large_graph_finds_single_improving_move(self):
        """Round-3 regression: on a 120-op chain where exactly one op
        has a better config, the search must find it.  The old
        delta/current acceptance (p(+1%) = 0.95) random-walked off the
        DP optimum on graphs this size and returned best == init."""
        lines = [
            "ffsim 1", "ndevices 4", "devices_per_node 4",
            "bw_intra 100", "bw_inter 10", "nops 120",
        ]
        for i in range(120):
            lines.append(f"op {i} 2 op{i}")
            # DP config: 4 shards of 10us; alternative: 2 shards of
            # 25us (worse) — except op 60, whose alternative is 2
            # shards of 1us with no sync (strictly better).
            lines.append("cfg 4 1 1 1 1 10.0 5.0 0 1 2 3")
            if i == 60:
                lines.append("cfg 2 1 1 1 1 1.0 0.0 0 1")
            else:
                lines.append("cfg 2 1 1 1 1 25.0 5.0 0 1")
        lines.append("nedges 0")
        p = "\n".join(lines) + "\n"
        res = ffsim_search(p, 20000, 0, 5.0)
        assert res["best_us"] < res["init_us"]
        assert res["assign"][60] == 1
        assert sum(res["assign"]) == 1  # and ONLY op 60 moved

    def test_inception_speedup_above_one(self):
        """VERDICT r2 item 4: the ICML'18 model family must show a
        simulated operator-parallel gain (coordinated per-branch h/w
        splits; see OP_PARALLEL.md for the v5e-roofline analysis)."""
        from flexflow_tpu.models.cnn_catalog import build_inception_v3

        res = search_strategy(
            build_inception_v3(batch_size=64), num_devices=4,
            iters=20_000, seed=0,
        )
        assert res.speedup > 1.03


def _mlp(batch=8, width=32, ndev_classes=4, seed=3):
    """Tiny MLP for execution-config search tests (fast compiles)."""
    import jax.numpy as jnp

    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.graph import FFModel

    ff = FFModel(FFConfig(batch_size=batch, seed=seed))
    x = ff.create_tensor((batch, width), name="x")
    lbl = ff.create_tensor((batch,), dtype=jnp.int32, name="label")
    t = ff.dense(x, width, activation="relu", name="fc1")
    t = ff.dense(t, width, activation="relu", name="fc2")
    t = ff.dense(t, ndev_classes, name="head")
    ff.softmax(t, lbl, name="softmax")
    return ff


class TestCalibration:
    """The dispatch/fence constant loader (search/cost_model.py):
    fitted from a run's own JSONL telemetry, with the measured-host
    defaults as the LOUD uncalibrated fallback (SEARCH.md protocol)."""

    def test_defaults_are_uncalibrated(self):
        from flexflow_tpu.search.cost_model import (
            DEFAULT_DISPATCH_MS,
            DEFAULT_FENCE_MS,
            Calibration,
        )

        cal = Calibration()
        assert not cal.calibrated
        assert cal.dispatch_ms == DEFAULT_DISPATCH_MS
        assert cal.fence_ms == DEFAULT_FENCE_MS
        assert "uncalibrated" in cal.describe()

    def test_from_run_end_calibration_block(self, tmp_path):
        """A complete log's run_end ``calibration`` block wins — the
        single-run protocol (OBSERVABILITY.md)."""
        import json

        from flexflow_tpu.search import Calibration

        path = tmp_path / "run-1.jsonl"
        events = [
            {"ev": "run_start", "seq": 0},
            {"ev": "step", "seq": 1, "wall_s": 0.004},
            {"ev": "run_end", "seq": 2, "calibration": {
                "steps": 30, "fences_per_step": 0.066,
                "programs_per_step": 16.0, "step_ms_p50": 17.6,
                "dispatch_ms_per_program": 1.1, "fence_ms": 0.9,
                "fence_samples": 2,
            }},
        ]
        path.write_text("\n".join(json.dumps(e) for e in events) + "\n")
        cal = Calibration.from_jsonl(str(path))
        assert cal.calibrated
        assert cal.dispatch_ms == pytest.approx(1.1)
        assert cal.fence_ms == pytest.approx(0.9)
        assert cal.step_ms_p50 == pytest.approx(17.6)
        assert cal.programs_per_step == pytest.approx(16.0)
        assert cal.steps == 30
        # Complete accounting + no `search` event: this run can anchor
        # the compute-scale fit.
        assert cal.complete and not cal.auto_executed

    def test_from_truncated_log_rederives(self, tmp_path):
        """A crashed run's log has no run_end: the constants re-derive
        from the raw step/fence/superstep events (min non-warmup fence
        = round-trip floor; step p50)."""
        import json

        from flexflow_tpu.search import Calibration

        path = tmp_path / "run-crashed.jsonl"
        events = (
            [{"ev": "run_start", "seq": 0}]
            + [{"ev": "fence", "label": "warmup", "wall_s": 0.5}]
            + [{"ev": "step", "step": i, "wall_s": 0.010 + 0.001 * (i % 3)}
               for i in range(9)]
            + [{"ev": "fence", "label": "log", "wall_s": 0.002},
               {"ev": "fence", "label": "log", "wall_s": 0.003}]
        )
        path.write_text("\n".join(json.dumps(e) for e in events)
                        + '\n{"torn tail')  # crashed mid-write
        cal = Calibration.from_jsonl(str(path))
        assert cal.calibrated
        assert cal.step_ms_p50 == pytest.approx(11.0)
        # min non-warmup fence, NOT the 500ms compile-inclusive warmup.
        assert cal.fence_ms == pytest.approx(2.0)
        assert cal.steps == 9
        # Truncated: programs-per-step may be unrecoverable, so this
        # source must NOT anchor the compute-scale fit.
        assert not cal.complete

    def test_missing_file_falls_back_loudly(self, tmp_path, caplog):
        from flexflow_tpu.search import Calibration

        with caplog.at_level(logging.WARNING, logger="ff.search"):
            cal = Calibration.from_jsonl(str(tmp_path / "nope.jsonl"))
        assert not cal.calibrated
        assert any("uncalibrated" in r.message for r in caplog.records)

    def test_from_dir_picks_latest_excluding_active(self, tmp_path):
        import json

        from flexflow_tpu.search import Calibration

        old = tmp_path / "run-a.jsonl"
        new = tmp_path / "run-b.jsonl"
        for p, fence in ((old, 3.0), (new, 7.0)):
            p.write_text(json.dumps({
                "ev": "run_end",
                "calibration": {"steps": 4, "fences_per_step": 1.0,
                                "fence_ms": fence, "fence_samples": 4},
            }) + "\n")
        os.utime(old, (1, 1))
        assert Calibration.from_dir(str(tmp_path)).fence_ms == 7.0
        # The ACTIVE run's own (still-empty) file must not self-feed.
        cal = Calibration.from_dir(str(tmp_path), exclude=str(new))
        assert cal.fence_ms == 3.0


class TestExecutionConfigAccounting:
    """programs/fences-per-step must be the EXACT formulas the run
    telemetry pins (OBSERVABILITY.md dispatch audit): ``2*S*ceil(m/c)``
    host-driven, ``1/k`` fused/compiled — the searcher optimizing any
    OTHER accounting would tune a phantom runtime."""

    def _ecfg(self, **kw):
        from flexflow_tpu.parallel.strategy import StrategyStore
        from flexflow_tpu.search.execution import ExecutionConfig

        return ExecutionConfig(store=StrategyStore.data_parallel(8), **kw)

    def test_host_pipeline_programs(self):
        assert self._ecfg(stages=4, microbatches=8).programs_per_step() == 64
        assert self._ecfg(
            stages=4, microbatches=8, chunk=8
        ).programs_per_step() == 8
        # Non-divisible chunk tail: ceil(8/3) = 3 chunk programs/stage.
        assert self._ecfg(
            stages=2, microbatches=8, chunk=3
        ).programs_per_step() == 2 * 2 * 3
        # Accum lowers onto the microbatch loop (a*m microbatches).
        assert self._ecfg(
            stages=2, microbatches=4, accum_steps=2
        ).programs_per_step() == 2 * 2 * 8

    def test_fused_paths_are_one_program_per_k(self):
        assert self._ecfg().programs_per_step() == 1.0
        assert self._ecfg(steps_per_call=8).programs_per_step() == 1 / 8
        assert self._ecfg(
            stages=4, microbatches=8, compiled=True, steps_per_call=8
        ).programs_per_step() == 1 / 8

    def test_fence_accounting(self):
        assert self._ecfg().fences_per_step() == 0.0  # unfenced k=1 loop
        assert self._ecfg(steps_per_call=8).fences_per_step() == 1 / 8
        # The loudly-warned clip-norm floor on the host-driven pipeline.
        assert self._ecfg(
            stages=4, microbatches=8
        ).fences_per_step(clip_norm=1.0) == 1.0
        assert self._ecfg(
            stages=4, microbatches=8, compiled=True
        ).fences_per_step(clip_norm=1.0) == 0.0  # device-side clip


# PIPELINE_OVERHEAD.md round 7 (2026-08-04, 8-dev virtual CPU mesh,
# 30 timed steps, same-day A/B) — the recorded dispatch-amortization
# sweeps the simulator must reproduce the ranking of.  ms/step.
_R7_DISPATCH_BOUND = {  # S=4 mb=8, b64 x w256: dispatch dominates
    "host_c1": 113.7,      # 64 programs/step
    "host_cm": 50.6,       # c=m=8 -> 8 programs/step
    "compiled": 43.4,      # 1 program/step
    "compiled_k8": 45.9,   # 1/8 programs/step (fence-neutral on CPU)
}
_R7_COMPUTE_BOUND = {  # S=2 mb=8, b512 x w1024: compute dominates
    "host_c1": 2308.0,     # 32 programs/step
    "host_cm": 1882.0,     # c=m=8 -> 4 programs/step
    "compiled": 1917.0,    # 1 program/step
}
# Same-day re-measurement drift on this box is ~7% (round 6/7 notes);
# measured pairs closer than that are ties the predictor need not
# (and cannot honestly) order.
_R7_NOISE = 1.07


class TestRankingConsistency:
    """ISSUE 6 acceptance: simulator-predicted ranking matches the
    MEASURED ranking across the dispatch-amortization variants at one
    dispatch-bound and one compute-bound shape — golden recorded
    constants, no live timing in tier-1."""

    def _predict(self, recorded, S, m, dispatch_ms):
        from flexflow_tpu.parallel.strategy import StrategyStore
        from flexflow_tpu.search.cost_model import Calibration
        from flexflow_tpu.search.execution import (
            REMAT_FACTOR,
            ExecutionConfig,
            predict_step_ms,
        )

        # The calibration protocol applied to the recorded sweep: the
        # compiled row is compute + ONE dispatch, so the recorded
        # compute term is its ms minus one program's dispatch.
        compute_us = (recorded["compiled"] - dispatch_ms) / REMAT_FACTOR * 1e3
        cal = Calibration(dispatch_ms=dispatch_ms, fence_ms=dispatch_ms,
                          calibrated=True)
        store = StrategyStore.data_parallel(8)
        variants = {
            "host_c1": ExecutionConfig(store=store, stages=S,
                                       microbatches=m, chunk=1),
            "host_cm": ExecutionConfig(store=store, stages=S,
                                       microbatches=m, chunk=m),
            "compiled": ExecutionConfig(store=store, stages=S,
                                        microbatches=m, compiled=True),
            "compiled_k8": ExecutionConfig(store=store, stages=S,
                                           microbatches=m, compiled=True,
                                           steps_per_call=8),
        }
        return {
            name: predict_step_ms(None, e, 8, calibration=cal,
                                  compute_us=compute_us)
            for name, e in variants.items()
        }

    def _assert_ranking_matches(self, recorded, predicted):
        """Every measured-distinguishable pair (outside the recorded
        noise floor) must be predicted in the measured order."""
        for a in recorded:
            for b in recorded:
                if recorded[a] > recorded[b] * _R7_NOISE:
                    assert predicted[a] > predicted[b], (
                        f"measured {a}={recorded[a]} > {b}={recorded[b]} "
                        f"but predicted {predicted[a]:.2f} <= "
                        f"{predicted[b]:.2f}"
                    )

    def test_dispatch_bound_shape(self):
        rec = _R7_DISPATCH_BOUND
        # Per-program host dispatch fitted from the sweep itself:
        # (c1 - compiled) / (64 - 1 programs) ~= 1.1 ms/program.
        dispatch_ms = (rec["host_c1"] - rec["compiled"]) / 63.0
        pred = self._predict(rec, S=4, m=8, dispatch_ms=dispatch_ms)
        self._assert_ranking_matches(rec, pred)
        # c1 is exact by construction; the INDEPENDENT c=m point must
        # land near its measured value (the linear-dispatch model).
        assert pred["host_c1"] == pytest.approx(rec["host_c1"], rel=1e-6)
        assert pred["host_cm"] == pytest.approx(rec["host_cm"], rel=0.15)
        # Dispatch amortization must never be predicted as a slowdown.
        assert pred["compiled_k8"] <= pred["compiled"]

    def test_compute_bound_shape(self):
        rec = _R7_COMPUTE_BOUND
        # Same host: the DISPATCH-bound sweep's constant carries over.
        dispatch_ms = (
            _R7_DISPATCH_BOUND["host_c1"] - _R7_DISPATCH_BOUND["compiled"]
        ) / 63.0
        pred = self._predict(rec, S=2, m=8, dispatch_ms=dispatch_ms)
        pred.pop("compiled_k8")  # not recorded at this shape
        self._assert_ranking_matches(rec, pred)
        # Where compute dominates, the predictor must NOT promise the
        # dispatch-bound win: predicted compiled-vs-c1 gain small here,
        # large at the dispatch-bound shape (matching 1.08x vs 2.6x
        # measured).
        gain_compute = pred["host_c1"] / pred["compiled"]
        assert gain_compute < 1.10
        pred_db = self._predict(_R7_DISPATCH_BOUND, S=4, m=8,
                                dispatch_ms=dispatch_ms)
        assert pred_db["host_c1"] / pred_db["compiled"] > 1.5


class TestExecutionSearch:
    """search_execution_config: the full execution-config space, with
    legality REUSED from the runtime so every emitted candidate is
    executor-legal (ISSUE 6 acceptance)."""

    def test_every_emitted_candidate_is_runnable(self, caplog):
        """Each config the searcher emits executes without a loud
        fallback — built via make_executor and trained one superstep's
        worth of iterations at ITS steps_per_call."""
        import jax

        from flexflow_tpu.optim import SGDOptimizer
        from flexflow_tpu.runtime.pipeline import (
            PipelineExecutor,
            make_executor,
        )
        from flexflow_tpu.runtime.trainer import Trainer
        from flexflow_tpu.search import search_execution_config

        ff = _mlp()
        res = search_execution_config(
            ff, 4, iters=200, seed=0, ks=(1, 4),
            stage_options=(2,), microbatch_options=(2,),
        )
        assert len(res.candidates) >= 4
        families = set()
        for ecfg in res.candidates:
            families.add((ecfg.stages, ecfg.compiled))
            with caplog.at_level(logging.WARNING):
                caplog.clear()
                ex = make_executor(
                    ff, ecfg.store if ecfg.store.table else None,
                    optimizer=SGDOptimizer(lr=0.01),
                    devices=jax.devices()[:4],
                    microbatches=ecfg.microbatches, chunk=ecfg.chunk,
                    compiled=ecfg.compiled,
                )
                stats = Trainer(ex).fit(
                    iterations=max(ecfg.steps_per_call, 1), warmup=0,
                    steps_per_call=ecfg.steps_per_call,
                )
            fallback = [
                r.message for r in caplog.records
                if "falling back" in r.message or "refus" in r.message
                or "unavailable" in r.message
            ]
            assert not fallback, (ecfg.describe(), fallback)
            # The requested dispatch form was REALIZED, not degraded.
            if ecfg.compiled:
                assert isinstance(ex, PipelineExecutor) and ex.compiled
            elif ecfg.layer_wise:
                assert isinstance(ex, PipelineExecutor) and not ex.compiled
            else:
                assert not isinstance(ex, PipelineExecutor)
            assert np.isfinite(stats["loss"])
        # The reduced space still exercised every family: full-mesh,
        # host-driven pipeline, compiled pipeline.
        assert (1, False) in families and (2, False) in families
        assert (2, True) in families

    def test_search_space_legality_reuse(self):
        """Candidate k-values route through the runtime's OWN
        superstep_mode: amortized strategies under --resilient stay at
        k=1 (the loop refuses k>1 there), compiled candidates appear
        only when compiled_unsupported_reason is None."""
        from flexflow_tpu.runtime.pipeline import (
            compiled_unsupported_reason,
        )
        from flexflow_tpu.search import search_execution_config

        ff = _mlp()
        res = search_execution_config(
            ff, 4, iters=0, seed=0, ks=(1, 4),
            stage_options=(2,), microbatch_options=(2,), resilient=True,
        )
        for c in res.candidates:
            if c.layer_wise and not c.compiled:
                assert c.steps_per_call == 1
            if c.compiled:
                assert compiled_unsupported_reason(ff, c.store) is None

    def test_calibration_steers_the_winner(self):
        """The dispatch term must actually steer: an expensive
        per-program host (relay-like) pushes the winner to the fused
        minimum-dispatch form; a free-dispatch host ranks by compute
        alone and keeps programs-per-step irrelevant."""
        from flexflow_tpu.search import Calibration, search_execution_config

        ff = _mlp()
        relay = search_execution_config(
            ff, 4, iters=0, seed=0, ks=(1, 8),
            stage_options=(2,), microbatch_options=(2,),
            calibration=Calibration(dispatch_ms=16.0, fence_ms=16.0,
                                    calibrated=True),
        )
        assert relay.best.programs_per_step() <= 1 / 8
        free = search_execution_config(
            ff, 4, iters=0, seed=0, ks=(1, 8),
            stage_options=(2,), microbatch_options=(2,),
            calibration=Calibration(dispatch_ms=0.0, fence_ms=0.0,
                                    calibrated=True),
        )
        by_compute = min(free.candidates, key=lambda c: c.compute_ms)
        assert free.best.predicted_ms == pytest.approx(
            by_compute.compute_ms
        )

    def test_compute_scale_fit_from_measured_p50(self):
        """A calibrated step_ms_p50 anchors the compute term: measured
        p50 minus the run's OWN dispatch/fence overhead is what the
        baseline's simulated compute must scale to."""
        from flexflow_tpu.search import Calibration, search_execution_config

        ff = _mlp()
        cal = Calibration(dispatch_ms=1.0, fence_ms=1.0, calibrated=True,
                          step_ms_p50=21.0, programs_per_step=1.0,
                          fences_per_step=0.0, steps=30, complete=True)
        res = search_execution_config(
            ff, 4, iters=0, seed=0, ks=(1,),
            stage_options=(2,), microbatch_options=(2,), calibration=cal,
        )
        # baseline = DP k=1: predicted = scale*compute + 1 dispatch
        # must equal the measured p50 the scale was solved from.
        assert res.baseline.predicted_ms == pytest.approx(21.0, rel=1e-6)
        assert res.compute_scale > 0

    def test_auto_run_calibration_does_not_anchor_scale(self, tmp_path):
        """A calibration log that carries a ``search`` event trained
        under an auto-CHOSEN config: its step p50 measures the winner,
        not the baseline, so the compute-scale fit must be skipped
        (the dispatch/fence constants still apply)."""
        import json

        from flexflow_tpu.search import Calibration, search_execution_config

        path = tmp_path / "run-auto.jsonl"
        events = [
            {"ev": "run_start"},
            {"ev": "search", "chosen": {"label": "won"}},
            {"ev": "run_end", "calibration": {
                "steps": 20, "fences_per_step": 0.0, "step_ms_p50": 5.0,
                "fence_ms": 1.25, "fence_samples": 1,
            }},
        ]
        path.write_text("\n".join(json.dumps(e) for e in events) + "\n")
        cal = Calibration.from_jsonl(str(path))
        assert cal.calibrated and cal.auto_executed
        res = search_execution_config(
            _mlp(), 4, iters=0, seed=0, ks=(1,),
            stage_options=(2,), microbatch_options=(2,), calibration=cal,
        )
        assert res.compute_scale == 1.0
        assert res.calibration.fence_ms == pytest.approx(1.25)

    def test_search_result_is_deterministic(self):
        from flexflow_tpu.search import search_execution_config

        ff = _mlp()
        a = search_execution_config(ff, 4, iters=300, seed=0,
                                    stage_options=(2,),
                                    microbatch_options=(2,))
        b = search_execution_config(ff, 4, iters=300, seed=0,
                                    stage_options=(2,),
                                    microbatch_options=(2,))
        assert a.best.describe() == b.best.describe()
        assert a.best.predicted_ms == pytest.approx(b.best.predicted_ms)

    def test_cli_auto_mode(self, tmp_path, capsys):
        """``python -m flexflow_tpu.search --auto`` prints the ranked
        execution configs + the app flags that run the winner, and
        still writes a loadable strategy file."""
        from flexflow_tpu.search.__main__ import main

        out = tmp_path / "strategy.json"
        assert main([
            "--model", "alexnet", "-b", "8", "--devices", "4",
            "--iters", "200", "--auto", "-o", str(out),
        ]) in (0, None)
        printed = capsys.readouterr().out
        assert "best    =" in printed
        assert "run it: -s" in printed
        assert "uncalibrated" in printed  # no calibration file given
        StrategyStore.load(str(out))

    def test_build_stage_partition_legality(self):
        """The synthetic stage-partition builder returns None (skip)
        rather than an illegal store: stage count vs ops, divisibility
        of the batch across microbatches x intra-stage DP."""
        from flexflow_tpu.search.problem import build_stage_partition

        ff = _mlp(batch=8)
        store = build_stage_partition(ff, 8, 2, microbatches=2)
        assert store is not None and store.layer_wise
        # 4 ops cannot split into 8 stages; 8 devices % 3 stages != 0.
        assert build_stage_partition(ff, 8, 8) is None
        assert build_stage_partition(ff, 8, 3) is None
        # batch 8 / m=4 = 2 rows, intra-stage DP n=4 cannot shard them.
        assert build_stage_partition(ff, 8, 2, microbatches=4) is None


class TestScheduleValidation:
    """ffsim self-check — the reference's VERBOSE schedule-consistency
    mode (``simulator.cc:1012-1031``): every compute/comm occupancy
    recorded and checked for per-resource overlap."""

    def test_valid_schedule_passes(self):
        from flexflow_tpu.native import ffsim_validate

        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 2",
            "op 0 1 producer",
            "cfg 2 1 1 1 1 5.0 0.0 0 1",
            "op 1 1 consumer",
            "cfg 1 2 1 1 1 7.0 0.0 0 1",
            "nedges 1",
            "edge 0 1 4 2 8 4 0 -1 -1 1",
        ])
        out = ffsim_validate(p, [0, 0])
        assert out["valid"] == 1
        # 2 producer shards + 2 consumer shards + 2 cross-device
        # transfers (each consumer pulls the remote half).
        assert out["ntasks"] == 6
        assert out["time_us"] == pytest.approx(16.2)

    def test_search_result_validates(self):
        res = search_strategy(
            build_alexnet(batch_size=64, image_size=229, num_classes=1000),
            num_devices=4, iters=2000, seed=0,
        )  # search_strategy itself runs ffsim_validate on the winner
        assert res.best_time_us <= res.dp_time_us

    def test_overlap_detected(self):
        from flexflow_tpu.native import ffsim_check_intervals

        ffsim_check_intervals([(0, 0.0, 5.0), (0, 5.0, 9.0), (1, 1.0, 2.0)])
        with pytest.raises(ValueError, match="schedule inconsistent"):
            ffsim_check_intervals([(0, 0.0, 5.0), (0, 4.0, 9.0)])

    def test_bad_bounds_detected(self):
        from flexflow_tpu.native import ffsim_check_intervals

        with pytest.raises(ValueError, match="schedule inconsistent"):
            ffsim_check_intervals([(0, -1.0, 5.0)])
        with pytest.raises(ValueError, match="schedule inconsistent"):
            ffsim_check_intervals([(0, 3.0, 2.0)])
        with pytest.raises(ValueError, match="schedule inconsistent"):
            ffsim_check_intervals([(0, 0.0, float("inf"))])
