"""Strategy-search subsystem: native simulator semantics + MCMC search.

The reference's equivalent is the offline simulator binary
(``scripts/simulator.cc``): event-driven list scheduling of shard +
comm tasks and Metropolis search.  The hand-computed schedule cases
here pin the scheduler's exact semantics (device timelines, channel
contention, rect-intersection comm volumes).
"""

import numpy as np
import pytest

from flexflow_tpu.models.alexnet import build_alexnet
from flexflow_tpu.native import ffsim_search, ffsim_simulate
from flexflow_tpu.parallel.strategy import AXES, ParallelConfig, StrategyStore
from flexflow_tpu.search import search_strategy, simulate_strategy
from flexflow_tpu.search.problem import build_virtual_plan, shard_devices


def _problem(lines):
    return "\n".join(lines) + "\n"


class TestSimulatorSemantics:
    def test_single_op_compute_only(self):
        # One op, 2 shards of 5us each on distinct devices -> 5us.
        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 1",
            "op 0 1 solo",
            "cfg 2 1 1 1 1 5.0 0.0 0 1",
            "nedges 0",
        ])
        assert ffsim_simulate(p, [0]) == pytest.approx(5.0)

    def test_sync_cost_added_after_op(self):
        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 1",
            "op 0 1 solo",
            "cfg 2 1 1 1 1 5.0 3.0 0 1",
            "nedges 0",
        ])
        assert ffsim_simulate(p, [0]) == pytest.approx(8.0)

    def test_resharding_comm_hand_schedule(self):
        # op0 n-split rows of an (8,4) f32 tensor; op1 c-splits columns
        # and broadcasts rows.  Each cross-device transfer moves half a
        # source shard: 8 elems * 4B / bw 10 + 1us latency = 4.2us.
        # Comm starts when the producer shard finishes (5us); consumer
        # shards start at 9.2 and run 7us -> makespan 16.2.
        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 2",
            "op 0 1 producer",
            "cfg 2 1 1 1 1 5.0 0.0 0 1",
            "op 1 1 consumer",
            "cfg 1 2 1 1 1 7.0 0.0 0 1",
            "nedges 1",
            "edge 0 1 4 2 8 4 0 -1 -1 1",
        ])
        assert ffsim_simulate(p, [0, 0]) == pytest.approx(16.2)

    def test_same_device_transfer_is_free(self):
        # Same split on both ops, same placement: no comm, pure chain.
        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 2",
            "op 0 1 a",
            "cfg 2 1 1 1 1 5.0 0.0 0 1",
            "op 1 1 b",
            "cfg 2 1 1 1 1 7.0 0.0 0 1",
            "nedges 1",
            "edge 0 1 4 2 8 4 0 -1 0 -1",
        ])
        assert ffsim_simulate(p, [0, 0]) == pytest.approx(12.0)

    def test_search_picks_obvious_winner(self):
        # Config 1 halves the time with no comm downside; MCMC must
        # find it and report the config-0 start as the baseline.
        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 1",
            "op 0 2 solo",
            "cfg 1 1 1 1 1 10.0 0.0 0",
            "cfg 2 1 1 1 1 5.0 0.0 0 1",
            "nedges 0",
        ])
        res = ffsim_search(p, iters=50, seed=0, alpha=5.0)
        assert res["init_us"] == pytest.approx(10.0)
        assert res["best_us"] == pytest.approx(5.0)
        assert res["assign"] == [1]

    def test_bad_problem_raises(self):
        with pytest.raises(ValueError):
            ffsim_simulate("not a problem", [0])

    def test_zero_config_op_raises_not_crashes(self):
        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 1", "op 0 0 empty", "nedges 0",
        ])
        with pytest.raises(ValueError):
            ffsim_search(p, iters=10, seed=0, alpha=5.0)

    def test_bad_edge_axis_raises_not_crashes(self):
        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 2",
            "op 0 1 a", "cfg 2 1 1 1 1 5.0 0.0 0 1",
            "op 1 1 b", "cfg 2 1 1 1 1 5.0 0.0 0 1",
            "nedges 1",
            "edge 0 1 4 1 8 7 0",  # src axis 7 out of range
        ])
        with pytest.raises(ValueError):
            ffsim_simulate(p, [0, 0])


class TestShardDevices:
    def test_data_parallel_covers_all_devices(self):
        plan = build_virtual_plan(8)
        assert shard_devices(plan, ParallelConfig(n=8)) == list(range(8))

    def test_hybrid_covers_all_devices_once(self):
        plan = build_virtual_plan(8)
        devs = shard_devices(plan, ParallelConfig(n=2, c=4))
        assert sorted(devs) == list(range(8))

    def test_partial_split_replicates_on_first_coords(self):
        plan = build_virtual_plan(8)
        devs = shard_devices(plan, ParallelConfig(n=2))
        assert len(devs) == 2
        assert len(set(devs)) == 2

    def test_explicit_device_ids_win(self):
        plan = build_virtual_plan(8)
        pc = ParallelConfig(c=4, device_ids=(3, 1, 2, 0))
        assert shard_devices(plan, pc) == [3, 1, 2, 0]



def _run_one_train_step(ff, store, n_classes, image, n_devices=8):
    """One executor train step under a strategy; asserts finite loss."""
    import jax

    from flexflow_tpu.optim import SGDOptimizer
    from flexflow_tpu.runtime.pipeline import make_executor

    ex = make_executor(ff, store, optimizer=SGDOptimizer(lr=0.01),
                       devices=jax.devices()[:n_devices])
    params, opt_state, state = ex.init()
    rng = np.random.default_rng(0)
    batch = ex.shard_batch({
        "image": rng.standard_normal(image).astype(np.float32),
        "label": rng.integers(0, n_classes, size=(image[0],)).astype(np.int32),
    })
    params, opt_state, state, metrics = ex.train_step(
        params, opt_state, state, batch
    )
    jax.block_until_ready(metrics)
    assert np.isfinite(float(metrics["train_loss"]))


class TestEndToEndSearch:
    @pytest.fixture(scope="class")
    def alexnet(self):
        return build_alexnet(batch_size=64, image_size=229, num_classes=1000)

    def test_search_beats_or_matches_dp(self, alexnet):
        res = search_strategy(alexnet, num_devices=8, iters=3000, seed=0)
        assert res.best_time_us <= res.dp_time_us
        # AlexNet's FC gradient sync makes DP clearly sub-optimal — the
        # ICML'18 result the search must reproduce in simulation.
        assert res.speedup > 1.5
        assert set(res.assignment) == {op.name for op in alexnet.layers}
        for pc in res.assignment.values():
            assert pc.num_parts <= 8

    def test_store_roundtrip_and_simulate(self, alexnet, tmp_path):
        res = search_strategy(alexnet, num_devices=8, iters=2000, seed=1)
        path = tmp_path / "strategy.json"
        res.store.save(str(path))
        loaded = StrategyStore.load(str(path))
        t = simulate_strategy(alexnet, loaded, 8)
        assert t == pytest.approx(res.best_time_us, rel=1e-6)

    def test_dp_store_matches_reported_baseline(self, alexnet):
        res = search_strategy(alexnet, num_devices=8, iters=100, seed=0)
        # A store with no entries = the runtime's DP fallback; candidate
        # 0 of every op is the same config, so times must agree.
        dp_t = simulate_strategy(alexnet, StrategyStore.data_parallel(8), 8)
        assert dp_t == pytest.approx(res.dp_time_us, rel=1e-6)

    def test_simulate_strategy_measured_costs(self, alexnet):
        """simulate_strategy prices ops from a measured table when
        given one (the ffsim-calibration path, tools/calibrate_ffsim)."""
        flat = {op.name: 1000.0 for op in alexnet.layers}
        store = StrategyStore.data_parallel(8)
        t_meas = simulate_strategy(alexnet, store, 8, measured_costs=flat)
        t_roof = simulate_strategy(alexnet, store, 8)
        assert t_meas != pytest.approx(t_roof)
        # 1000 us/op fwd (x the fwd+bwd factor) across a sequential
        # graph: the makespan must scale with op count.
        assert t_meas > 1000.0 * len(alexnet.layers) / 8

    def test_measured_costs_override_roofline(self, alexnet):
        """Per-op measured times (runtime.profiler.measured_cost_table
        format) replace the roofline estimate and change the simulated
        baseline accordingly."""
        flat = {op.name: 1000.0 for op in alexnet.layers}
        res = search_strategy(
            alexnet, num_devices=8, iters=100, seed=0, measured_costs=flat
        )
        res2 = search_strategy(alexnet, num_devices=8, iters=100, seed=0)
        assert res.dp_time_us != pytest.approx(res2.dp_time_us)
        assert res.best_time_us <= res.dp_time_us

    def test_cli_measured_mode(self, tmp_path, capsys):
        """``python -m flexflow_tpu.search --measured`` microbenches
        every op live (the reference's measured simulator inputs,
        ``scripts/cnn.h:204+``) and still emits a loadable strategy."""
        from flexflow_tpu.search.__main__ import main

        out = tmp_path / "strategy.json"
        assert main([
            "--model", "alexnet", "-b", "2", "--devices", "4",
            "--iters", "200", "--measured", "-o", str(out),
        ]) in (0, None)
        assert "measured 13 op costs" in capsys.readouterr().out
        loaded = StrategyStore.load(str(out))
        assert loaded.num_devices == 4

    def test_searched_strategy_runs_on_executor(self, alexnet):
        """The emitted table must be consumable by the runtime: compile
        and run one train step under the searched strategy on the
        8-device CPU mesh."""
        from flexflow_tpu.models.alexnet import build_alexnet as _b

        ff = _b(batch_size=8, image_size=67, num_classes=10)
        res = search_strategy(ff, num_devices=8, iters=500, seed=0)
        _run_one_train_step(ff, res.store, 10, (8, 67, 67, 3))

    def test_inception_op_parallel_strategy_runs(self):
        """BASELINE config #2: Inception-V3 blocks under a searched
        n/c/h/w operator-parallel strategy on 4 chips (virtual mesh).
        The searched table must beat or match simulated DP and run."""
        from flexflow_tpu.models import build_inception_v3

        ff = build_inception_v3(batch_size=4, image_size=75, num_classes=8)
        res = search_strategy(ff, num_devices=4, iters=300, seed=0)
        assert res.best_time_us <= res.dp_time_us * (1 + 1e-6)
        # At least one op got a non-pure-data-parallel config.
        assert any(
            pc.degree("c") > 1 or pc.degree("h") > 1 or pc.degree("w") > 1
            for pc in res.assignment.values()
        )
        _run_one_train_step(ff, res.store, 8, (4, 75, 75, 3), n_devices=4)

    def test_bad_edge_rank_raises_not_crashes(self):
        # nd = -1 previously hit vector::resize -> std::terminate.
        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 2",
            "op 0 1 a", "cfg 2 1 1 1 1 5.0 0.0 0 1",
            "op 1 1 b", "cfg 2 1 1 1 1 5.0 0.0 0 1",
            "nedges 1",
            "edge 0 1 4 -1",
        ])
        with pytest.raises(ValueError):
            ffsim_simulate(p, [0, 0])

    def test_oversized_counts_raise_not_allocate(self):
        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 2000000000",
        ])
        with pytest.raises(ValueError):
            ffsim_simulate(p, [0])

    def test_degree_exceeding_ndevices_raises(self):
        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 1", "op 0 1 a", "cfg 4 1 1 1 1 5.0 0.0 0 1 2 3",
            "nedges 0",
        ])
        with pytest.raises(ValueError):
            ffsim_simulate(p, [0])


class TestDeviceShiftedCandidates:
    def test_candidates_include_shifted_blocks(self):
        """Pure-n sub-mesh candidates exist on every aligned block, not
        just the mesh origin (the reference's per-table DLRM pinning
        freedom, dlrm_strategy.cc:11-19)."""
        from flexflow_tpu.config import FFConfig
        from flexflow_tpu.graph import FFModel
        from flexflow_tpu.search.problem import enumerate_candidates

        ff = FFModel(FFConfig(batch_size=8))
        x = ff.create_tensor((8, 16), name="x")
        ff.dense(x, 16, name="fc")
        plan = build_virtual_plan(4)
        cands = enumerate_candidates(ff.layers[0], plan)
        ids = {pc.device_ids for pc in cands if pc.device_ids is not None}
        assert (1,) in ids and (2,) in ids and (3,) in ids
        assert (2, 3) in ids

    def test_searched_placement_table_executes(self):
        """A searched table that mixes full-mesh and pinned ops (every
        op carrying explicit device_ids) must run via make_executor."""
        import jax

        from flexflow_tpu.config import FFConfig
        from flexflow_tpu.graph import FFModel
        from flexflow_tpu.optim import SGDOptimizer
        from flexflow_tpu.runtime.pipeline import make_executor

        ff = FFModel(FFConfig(batch_size=8))
        import jax.numpy as jnp

        ids_t = ff.create_tensor((8, 2), dtype=jnp.int32, name="ids")
        lbl = ff.create_tensor((8,), dtype=jnp.int32, name="label")
        e = ff.multi_embedding(ids_t, 2, 16, 4, name="tables")
        e = ff.reshape(e, (8, 8), name="r")
        t = ff.dense(e, 8, activation="relu", name="fc1")
        t = ff.dense(t, 4, name="fc2")
        ff.softmax(t, lbl, name="softmax")

        store = StrategyStore(4)
        # tables pinned off-origin, trunk on the full mesh.
        store.set("tables", ParallelConfig(device_ids=(2,)))
        for name in ("r", "fc1", "fc2", "softmax"):
            store.set(name, ParallelConfig(n=4, device_ids=(0, 1, 2, 3)))
        t_sim = simulate_strategy(ff, store, 4)
        assert np.isfinite(t_sim) and t_sim > 0
        ex = make_executor(ff, store, optimizer=SGDOptimizer(lr=0.1),
                           devices=jax.devices()[:4])
        params, opt_state, state = ex.init()
        rng = np.random.default_rng(0)
        batch = ex.shard_batch({
            "ids": rng.integers(0, 16, size=(8, 2)).astype(np.int32),
            "label": rng.integers(0, 4, size=(8,)).astype(np.int32),
        })
        params, opt_state, state, m = ex.train_step(
            params, opt_state, state, batch
        )
        assert np.isfinite(float(jax.device_get(m["train_loss"])))


class TestMeasuredDegrees:
    """Per-(op, degree) measured cost tables (the reference's
    ``computeTime[config]`` cache filled by live microbenchmarks per
    parallel degree, ``scripts/cnn.h:204-260``, ``simulator.cc:
    142-151``) replacing the whole-op / num_parts linear assumption."""

    def _model(self):
        import jax.numpy as jnp

        from flexflow_tpu.config import FFConfig
        from flexflow_tpu.graph import FFModel

        batch = 8
        ff = FFModel(FFConfig(batch_size=batch))
        x = ff.create_tensor((batch, 1024), name="x")
        lbl = ff.create_tensor((batch,), dtype=jnp.int32, name="label")
        t = ff.dense(x, 1024, activation="relu", name="fc")
        t = ff.dense(t, 16, name="head")
        ff.softmax(t, lbl, name="softmax")
        return ff

    def test_shard_local_shapes(self):
        from flexflow_tpu.runtime.profiler import _shard_shapes

        ff = self._model()
        fc = ff.layers[0]
        xs, ps, _ = _shard_shapes(fc, ParallelConfig(n=2, c=4))
        # Input: batch split by n, contracted feature dim kept FULL.
        assert xs == [(4, 1024)]
        # Kernel rows (out features, 'c') split 4-ways; bias likewise.
        assert ps["kernel"] == (256, 1024)
        assert ps["bias"] == (256,)

    def test_structural_cache_dedupes(self):
        """Identical shard geometries (same type/attrs/local shapes)
        are measured once — the reference's computeTime[] keyed by op
        hash + config (``simulator.cc:142-151``)."""
        from flexflow_tpu.runtime.profiler import measured_degree_table

        import jax.numpy as jnp

        from flexflow_tpu.config import FFConfig
        from flexflow_tpu.graph import FFModel

        calls = []

        def measure(op, pc, p, xs, s):
            calls.append((op.name, tuple(x.shape for x in xs)))
            return 10.0

        # Two structurally identical dense layers (the repeated-block
        # Inception case): the second one's candidates must all hit
        # the first one's cache entries.
        ff = FFModel(FFConfig(batch_size=8))
        x = ff.create_tensor((8, 64), name="x")
        lbl = ff.create_tensor((8,), dtype=jnp.int32, name="label")
        t = ff.dense(x, 64, activation="relu", name="fc1")
        t = ff.dense(t, 64, activation="relu", name="fc2")
        ff.softmax(t, lbl, name="softmax")
        table = measured_degree_table(ff, 8, measure=measure)
        assert set(table) == {"fc1", "fc2", "softmax"}
        assert table["fc1"] == table["fc2"]
        assert not any(name == "fc2" for name, _ in calls)
        assert all(us > 0 for v in table.values() for us in v.values())

    def test_measured_search_diverges_from_roofline(self):
        """The VERDICT-item acceptance: measured per-degree costs make
        the search pick a different (simulated-better-under-measure)
        strategy than the roofline on the same graph.  The injected
        measure models an MXU utilization floor: per-shard time scales
        with local rows but TP shards pay a fixed small-tile penalty —
        exactly the nonlinearity the old measured/parts linear scaling
        could not express."""
        from flexflow_tpu.runtime.profiler import measured_degree_table

        ff = self._model()
        roofline = search_strategy(ff, num_devices=8, iters=5000, seed=0)
        # Roofline: the big fc weight makes DP grad-sync dominant, so
        # the search tensor-parallelizes fc.
        assert roofline.assignment["fc"].c > 1

        def measure(op, pc, p, xs, s):
            return 10.0 * xs[0].shape[0] + 200.0 * (pc.degree("c") - 1)

        table = measured_degree_table(ff, 8, measure=measure)
        measured = search_strategy(
            ff, num_devices=8, iters=5000, seed=0, measured_costs=table
        )
        assert measured.assignment["fc"].c == 1
        assert measured.assignment["fc"] != roofline.assignment["fc"]

    def test_measured_bwd_asymmetry_changes_strategy(self):
        """VERDICT r4 acceptance: an op whose BACKWARD cost scales
        differently from its forward must steer the search away from
        the strategy the legacy fwd-only x3.0 assumption picks — the
        reason the reference measures ``t1+t2+t3`` per config instead
        of scaling forward (``scripts/cnn.h:252-277``)."""
        from flexflow_tpu.runtime.profiler import measured_degree_table

        ff = self._model()

        def fwd_only(op, pc, p, xs, s):
            # Legacy scalar entries: downstream applies x3.0.
            return 10.0 * xs[0].shape[0]

        def fwd_bwd(op, pc, p, xs, s):
            # Identical forward; backward pays a per-degree penalty
            # under c-splits (the conv-halo / embedding-scatter shape
            # of asymmetry) that no fwd-derived factor can express.
            fwd = 10.0 * xs[0].shape[0]
            return (fwd, 2.0 * fwd + 500.0 * (pc.degree("c") - 1))

        legacy = search_strategy(
            ff, num_devices=8, iters=5000, seed=0,
            measured_costs=measured_degree_table(ff, 8, measure=fwd_only),
        )
        measured = search_strategy(
            ff, num_devices=8, iters=5000, seed=0,
            measured_costs=measured_degree_table(ff, 8, measure=fwd_bwd),
        )
        # Same forward numbers; only the measured bwd leg differs —
        # the big fc flips from TP (grad-sync relief) to replicated.
        assert legacy.assignment["fc"].c > 1
        assert measured.assignment["fc"].c == 1
        assert measured.assignment["fc"] != legacy.assignment["fc"]

    def test_real_timing_smoke(self):
        """The real two-point fori_loop timer produces positive,
        finite per-degree times on the CPU backend for a tiny model
        and the search consumes them end to end."""
        import jax.numpy as jnp

        from flexflow_tpu.config import FFConfig
        from flexflow_tpu.graph import FFModel
        from flexflow_tpu.runtime.profiler import measured_degree_table

        ff = FFModel(FFConfig(batch_size=8))
        x = ff.create_tensor((8, 32), name="x")
        lbl = ff.create_tensor((8,), dtype=jnp.int32, name="label")
        t = ff.dense(x, 16, activation="relu", name="fc")
        ff.softmax(t, lbl, name="softmax")
        table = measured_degree_table(ff, 4, loops=(2, 6))
        assert table
        for v in table.values():
            for fwd_us, bwd_us in v.values():
                assert np.isfinite(fwd_us) and fwd_us > 0
                assert np.isfinite(bwd_us) and bwd_us >= 0
        res = search_strategy(
            ff, num_devices=4, iters=1000, seed=0, measured_costs=table
        )
        assert res.best_time_us > 0


class TestSearchTemperature:
    def test_large_graph_finds_single_improving_move(self):
        """Round-3 regression: on a 120-op chain where exactly one op
        has a better config, the search must find it.  The old
        delta/current acceptance (p(+1%) = 0.95) random-walked off the
        DP optimum on graphs this size and returned best == init."""
        lines = [
            "ffsim 1", "ndevices 4", "devices_per_node 4",
            "bw_intra 100", "bw_inter 10", "nops 120",
        ]
        for i in range(120):
            lines.append(f"op {i} 2 op{i}")
            # DP config: 4 shards of 10us; alternative: 2 shards of
            # 25us (worse) — except op 60, whose alternative is 2
            # shards of 1us with no sync (strictly better).
            lines.append("cfg 4 1 1 1 1 10.0 5.0 0 1 2 3")
            if i == 60:
                lines.append("cfg 2 1 1 1 1 1.0 0.0 0 1")
            else:
                lines.append("cfg 2 1 1 1 1 25.0 5.0 0 1")
        lines.append("nedges 0")
        p = "\n".join(lines) + "\n"
        res = ffsim_search(p, 20000, 0, 5.0)
        assert res["best_us"] < res["init_us"]
        assert res["assign"][60] == 1
        assert sum(res["assign"]) == 1  # and ONLY op 60 moved

    def test_inception_speedup_above_one(self):
        """VERDICT r2 item 4: the ICML'18 model family must show a
        simulated operator-parallel gain (coordinated per-branch h/w
        splits; see OP_PARALLEL.md for the v5e-roofline analysis)."""
        from flexflow_tpu.models.cnn_catalog import build_inception_v3

        res = search_strategy(
            build_inception_v3(batch_size=64), num_devices=4,
            iters=20_000, seed=0,
        )
        assert res.speedup > 1.03


class TestScheduleValidation:
    """ffsim self-check — the reference's VERBOSE schedule-consistency
    mode (``simulator.cc:1012-1031``): every compute/comm occupancy
    recorded and checked for per-resource overlap."""

    def test_valid_schedule_passes(self):
        from flexflow_tpu.native import ffsim_validate

        p = _problem([
            "ffsim 1", "ndevices 2", "devices_per_node 2",
            "bw_intra 10", "bw_inter 1",
            "nops 2",
            "op 0 1 producer",
            "cfg 2 1 1 1 1 5.0 0.0 0 1",
            "op 1 1 consumer",
            "cfg 1 2 1 1 1 7.0 0.0 0 1",
            "nedges 1",
            "edge 0 1 4 2 8 4 0 -1 -1 1",
        ])
        out = ffsim_validate(p, [0, 0])
        assert out["valid"] == 1
        # 2 producer shards + 2 consumer shards + 2 cross-device
        # transfers (each consumer pulls the remote half).
        assert out["ntasks"] == 6
        assert out["time_us"] == pytest.approx(16.2)

    def test_search_result_validates(self):
        res = search_strategy(
            build_alexnet(batch_size=64, image_size=229, num_classes=1000),
            num_devices=4, iters=2000, seed=0,
        )  # search_strategy itself runs ffsim_validate on the winner
        assert res.best_time_us <= res.dp_time_us

    def test_overlap_detected(self):
        from flexflow_tpu.native import ffsim_check_intervals

        ffsim_check_intervals([(0, 0.0, 5.0), (0, 5.0, 9.0), (1, 1.0, 2.0)])
        with pytest.raises(ValueError, match="schedule inconsistent"):
            ffsim_check_intervals([(0, 0.0, 5.0), (0, 4.0, 9.0)])

    def test_bad_bounds_detected(self):
        from flexflow_tpu.native import ffsim_check_intervals

        with pytest.raises(ValueError, match="schedule inconsistent"):
            ffsim_check_intervals([(0, -1.0, 5.0)])
        with pytest.raises(ValueError, match="schedule inconsistent"):
            ffsim_check_intervals([(0, 3.0, 2.0)])
        with pytest.raises(ValueError, match="schedule inconsistent"):
            ffsim_check_intervals([(0, 0.0, float("inf"))])
