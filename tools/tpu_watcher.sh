#!/usr/bin/env bash
# Probe the axon tunnel until it recovers, then run the round-5
# measurement sequence.  If the tunnel dies mid-sequence (the sequence
# aborts between steps on a failed probe), go back to probing and
# re-run on the next recovery — the bench persistence ladder and
# per-step logs make re-runs safe.  Exits only when the sequence
# completes end-to-end.
#
# The probe itself is the sanctioned safe check (subprocess under a
# hard timeout, tools/probe_tpu.py); the sequence steps are never
# timeout-killed.
#
# Liveness: this watcher and the in-process telemetry stall watchdog
# (runtime/telemetry.py; OBSERVABILITY.md) share ONE signal — the
# heartbeat file.  FF_HEARTBEAT_FILE below points every telemetry-
# enabled run at $OUT/heartbeat, which the run touches on each
# completed step and fence edge; FF_TELEMETRY_DIR turns telemetry on
# for the whole sequence so the heartbeat actually flows (and every
# run leaves a JSONL event log for the postmortem).  On an aborted
# sequence the watcher reports the heartbeat age: a FRESH heartbeat
# with a dead sequence means the wedge hit between runs; a STALE one
# names how long ago the last in-process progress happened — the same
# number the in-process watchdog warned about.
#
# Usage: bash tools/tpu_watcher.sh [interval_s]
set -u
cd "$(dirname "$0")/.."
OUT="${FF_MEASURED_DIR:-MEASURED_r5}"
mkdir -p "$OUT"
INTERVAL="${1:-360}"

export FF_HEARTBEAT_FILE="${FF_HEARTBEAT_FILE:-$OUT/heartbeat}"
export FF_TELEMETRY_DIR="${FF_TELEMETRY_DIR:-$OUT/telemetry}"

hb_age() {
  if [ -f "$FF_HEARTBEAT_FILE" ]; then
    echo "$(( $(date +%s) - $(stat -c %Y "$FF_HEARTBEAT_FILE") ))"
  else
    echo "-1"
  fi
}

while true; do
  if python tools/probe_tpu.py --timeout 120 >> "$OUT/watcher.log" 2>&1; then
    echo "tunnel UP at $(date -u +%FT%TZ) — starting r5 sequence" | tee -a "$OUT/watcher.log"
    bash tools/run_r5_measurements.sh >> "$OUT/watcher.log" 2>&1
    rc=$?
    echo "sequence exited rc=$rc at $(date -u +%FT%TZ)" | tee -a "$OUT/watcher.log"
    if [ "$rc" -eq 0 ]; then
      exit 0
    fi
    age="$(hb_age)"
    if [ "$age" -ge 0 ]; then
      echo "last in-process heartbeat ${age}s ago ($FF_HEARTBEAT_FILE)" | tee -a "$OUT/watcher.log"
    else
      echo "no heartbeat file yet ($FF_HEARTBEAT_FILE): sequence died before any telemetry-enabled step" | tee -a "$OUT/watcher.log"
    fi
    echo "sequence aborted (tunnel died mid-run?) — re-arming watcher" | tee -a "$OUT/watcher.log"
  fi
  sleep "$INTERVAL"
done
