#!/usr/bin/env bash
# Probe the axon tunnel until it recovers, then run the round-5
# measurement sequence once and exit.  Runs detached in the
# background; exit (success or sequence abort) is the signal that
# either measurements landed or the tunnel dropped mid-sequence.
#
# The probe itself is the sanctioned safe check (subprocess under a
# hard timeout, tools/probe_tpu.py); the sequence steps are never
# timeout-killed.
#
# Usage: bash tools/tpu_watcher.sh [interval_s]
set -u
cd "$(dirname "$0")/.."
OUT="${FF_MEASURED_DIR:-MEASURED_r5}"
mkdir -p "$OUT"
INTERVAL="${1:-600}"

while true; do
  if python tools/probe_tpu.py --timeout 120 >> "$OUT/watcher.log" 2>&1; then
    echo "tunnel UP at $(date -u +%FT%TZ) — starting r5 sequence" | tee -a "$OUT/watcher.log"
    bash tools/run_r5_measurements.sh >> "$OUT/watcher.log" 2>&1
    rc=$?
    echo "sequence exited rc=$rc at $(date -u +%FT%TZ)" | tee -a "$OUT/watcher.log"
    exit "$rc"
  fi
  sleep "$INTERVAL"
done
