#!/usr/bin/env bash
# Probe the axon tunnel until it recovers, then run the round-5
# measurement sequence.  If the tunnel dies mid-sequence (the sequence
# aborts between steps on a failed probe), go back to probing and
# re-run on the next recovery — the bench persistence ladder and
# per-step logs make re-runs safe.  Exits only when the sequence
# completes end-to-end.
#
# The probe itself is the sanctioned safe check (subprocess under a
# hard timeout, tools/probe_tpu.py); the sequence steps are never
# timeout-killed.
#
# Usage: bash tools/tpu_watcher.sh [interval_s]
set -u
cd "$(dirname "$0")/.."
OUT="${FF_MEASURED_DIR:-MEASURED_r5}"
mkdir -p "$OUT"
INTERVAL="${1:-360}"

while true; do
  if python tools/probe_tpu.py --timeout 120 >> "$OUT/watcher.log" 2>&1; then
    echo "tunnel UP at $(date -u +%FT%TZ) — starting r5 sequence" | tee -a "$OUT/watcher.log"
    bash tools/run_r5_measurements.sh >> "$OUT/watcher.log" 2>&1
    rc=$?
    echo "sequence exited rc=$rc at $(date -u +%FT%TZ)" | tee -a "$OUT/watcher.log"
    if [ "$rc" -eq 0 ]; then
      exit 0
    fi
    echo "sequence aborted (tunnel died mid-run?) — re-arming watcher" | tee -a "$OUT/watcher.log"
  fi
  sleep "$INTERVAL"
done
