#!/usr/bin/env bash
# Fast pre-commit smoke: the targeted suites from CLAUDE.md covering
# ops/oracles, strategy numerics, the pipeline runtime (incl. the
# chunked-scan dispatch + pipeline-superstep numerics,
# test_pipeline_chunk.py), superstep execution, the resilience/
# checkpoint subsystem, the run-telemetry layer, the streaming data
# plane (test_data_stream.py, DATA.md), the multi-host elastic
# layer (test_distributed.py + test_elastic.py fast cases; the live
# 2-process rig cases are @slow), and the
# strategy/execution search — ~5 min on the 8-dev virtual CPU mesh,
# vs ~14 min+ for the full suite.  Cases marked @pytest.mark.slow are
# excluded here as in the tier-1 budget run; they stay covered by the
# per-area targeted suites run WITHOUT the -m filter (CLAUDE.md
# "Tests", pytest.ini).  Single core box: no pytest-xdist.
#
# Usage: ./tools/tier1_smoke.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."
# fflint first (ANALYSIS.md): AST rules + the trace-only program audit
# (< 60 s) — the invariant gate runs before the suites that depend on
# the invariants.
env PYTHONPATH="$(pwd)" JAX_PLATFORMS=cpu \
    python -m flexflow_tpu.analysis --fast
# obs compare --gate A/A self-check: two identical telemetered dry-run
# logs must read `ok` (any drift verdict here means the comparator or
# the telemetry schema broke — the round-6 sentry's own sanity leg).
AA_DIR=$(mktemp -d /tmp/tier1_obs_aa.XXXXXX)
trap 'rm -rf "$AA_DIR"' EXIT
for leg in a b; do
    env PYTHONPATH="$(pwd)" JAX_PLATFORMS=cpu \
        python -m flexflow_tpu.apps.alexnet --dry-run \
        --telemetry "$AA_DIR/$leg" > /dev/null
done
env PYTHONPATH="$(pwd)" \
    python -m flexflow_tpu.obs compare "$AA_DIR/a" "$AA_DIR/b" --gate \
    > /dev/null
echo "obs compare --gate A/A: ok"
rm -rf "$AA_DIR"   # exec below replaces the shell; the trap won't fire
trap - EXIT
exec env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_ops.py \
    tests/test_analysis.py \
    tests/test_sharding_equivalence.py \
    tests/test_pipeline.py \
    tests/test_pipeline_chunk.py \
    tests/test_superstep.py \
    tests/test_resilience.py \
    tests/test_checkpoint.py \
    tests/test_distributed.py \
    tests/test_elastic.py \
    tests/test_telemetry.py \
    tests/test_obs.py \
    tests/test_spans.py \
    tests/test_data_stream.py \
    tests/test_serving.py \
    tests/test_serving_sched.py \
    tests/test_serving_fleet.py \
    tests/test_search.py \
    -q -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly "$@"
