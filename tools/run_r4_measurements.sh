#!/usr/bin/env bash
# Round-4 live-TPU measurement sequence.  Each step is gated by a
# fresh tunnel probe (a wedged relay hangs every new backend init, so
# continuing blind would just queue more hung processes), runs to
# completion (NEVER timeout-killed), and logs into MEASURED_r4/.
#
# Usage: bash tools/run_r4_measurements.sh [from_step]
set -u
cd "$(dirname "$0")/.."
OUT=MEASURED_r4
mkdir -p "$OUT"
FROM="${1:-1}"

probe() {
  python tools/probe_tpu.py --timeout 120 || {
    echo "tunnel DOWN before step $1 — stopping sequence" | tee -a "$OUT/sequence.log"
    exit 1
  }
}

step() {  # step <n> <name> <cmd...>
  local n="$1" name="$2"; shift 2
  [ "$n" -lt "$FROM" ] && return 0
  probe "$n"
  echo "=== step $n: $name ($(date -u +%FT%TZ))" | tee -a "$OUT/sequence.log"
  "$@" > "$OUT/$name.log" 2>&1
  echo "rc=$? $(date -u +%FT%TZ)" >> "$OUT/$name.log"
  tail -3 "$OUT/$name.log" | sed 's/^/    /'
}

# 1. Mosaic correctness probes (incl. the new 16k chunked flash and
# the double-buffered scatter's duplicate-distance stress).
step 1 probe_kernels python tools/probe_r4_kernels.py

# 2. Full headline bench EARLY: if the tunnel dies mid-sequence the
# round still has its primary artifact (writes one-line JSON to log).
step 2 bench python bench.py

# 3. Flash fwd variants race (chain-timed).
step 3 flash_variants python tools/probe_flash_variants.py 16 8 2048 64 --blocks=256,512

# 4. Flash bwd variants race (production vs 128-lane lse/delta).
step 4 flash_bwd_variants python tools/probe_flash_bwd_variants.py 16 8 2048 64 --blocks=256,512

# 5. Block sweep with the chain-timed protocol (fwd and fwd+bwd).
step 5 sweep_flash python tools/sweep_flash.py

# 6. Transformer step decomposition (layer slope + b32 remat + chunk race).
step 6 lm_decomp python tools/profile_lm_decomp.py

# 7. XProf device-plane op breakdown of the fused train step.
step 7 lm_trace python tools/profile_lm_trace.py "$OUT/lm_trace_dir"

# 8. Measured-mode strategy search artifact (reference cnn.h:204+ mode).
step 8 search_measured python -m flexflow_tpu.search --model alexnet -b 256 \
  --devices 4 --measured -o "$OUT/alexnet_strategy_measured.json"

echo "sequence complete" | tee -a "$OUT/sequence.log"
