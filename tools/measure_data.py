#!/usr/bin/env python
"""Streaming data-plane A/B: the DATA.md acceptance run on the 8-dev
virtual CPU mesh.

Three measurements, each against its acceptance bar:

- ``stream_vs_zc``: out-of-core StreamingLoader (reader thread +
  windowed shuffle + PrefetchLoader H2D overlap, dataset = 4x the
  shuffle window) vs the device-resident zero-copy loader on the SAME
  arrays.  Bar: >= 0.9x — streaming trades a bounded slowdown for
  unbounded dataset size.
- ``overlap_speedup``: streaming vs unprefetched inline reads when the
  source is throttled with a per-row disk-latency model (the SAME
  throttle both ways).  Bar: >= 1.3x — the reader thread + prefetch
  must actually hide the read behind compute.
- ``input-wait audit``: one telemetry-enabled streaming run; the
  summary's ``input_wait_s_total`` must equal the sum of the JSONL
  ``input_wait`` events' ``wall_s`` EXACTLY (the accounting is the
  same rounded number on both sides), and ``input_waits`` must equal
  the event count.

CPU wall noise at these sizes swings more between identical runs than
the effects being measured, so the protocol is the paired one from
measure_telemetry.py: each rep runs the two variants back to back
(order alternating between reps) and the statistic is the MEDIAN OF
PER-PAIR RATIOS; an ``a_a`` control column runs the protocol on two
identical legs — read each ratio against it.

Usage: env PYTHONPATH=/root/repo python tools/measure_data.py
       [--reps N] [--iters N] [--tpu]
(CPU runs re-exec in a clean JAX_PLATFORMS=cpu subprocess with the
axon sitecustomize dropped, per CLAUDE.md; --tpu keeps the relay on
PYTHONPATH and runs on the live chip.)
"""

import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def parent(argv):
    env = dict(os.environ)
    if "--tpu" in argv:
        env["PYTHONPATH"] = "/root/.axon_site:" + REPO
    else:
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    return subprocess.call(
        [sys.executable, os.path.abspath(__file__), "--child"] + argv,
        env=env,
    )


def _arg(argv, flag, default):
    if flag in argv:
        return int(argv[argv.index(flag) + 1])
    return default


def child(argv):
    # A tpu_watcher.sh environment's FF_TELEMETRY_DIR would install
    # file-backed telemetry on the supposedly-bare legs and skew every
    # pair; the audit leg builds its own Telemetry explicitly.
    os.environ.pop("FF_TELEMETRY_DIR", None)
    import jax

    if "--tpu" not in argv:
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.data.loader import (
        ArrayDataLoader,
        DeviceMemoryError,
        DeviceResidentLoader,
        PrefetchLoader,
    )
    from flexflow_tpu.data.stream import (
        ArrayStreamSource,
        StreamingLoader,
        ThrottledSource,
    )
    from flexflow_tpu.graph import FFModel
    from flexflow_tpu.optim import SGDOptimizer
    from flexflow_tpu.runtime.executor import Executor
    from flexflow_tpu.runtime.telemetry import Telemetry
    from flexflow_tpu.runtime.trainer import Trainer

    reps = _arg(argv, "--reps", 9)
    iters = _arg(argv, "--iters", 64)
    batch, width = 32, 64
    rows = batch * 8  # dataset = 8 batches; window = rows/4 => 4x bar
    nd = len(jax.devices())

    rng = np.random.default_rng(11)
    arrays = {
        "x": rng.standard_normal((rows, width)).astype(np.float32),
        "label": rng.integers(0, 8, size=(rows,)).astype(np.int32),
    }

    ff = FFModel(FFConfig(batch_size=batch, seed=7))
    x = ff.create_tensor((batch, width), name="x")
    lbl = ff.create_tensor((batch,), dtype=np.int32, name="label")
    t = ff.dense(x, width, activation="relu", name="fc1")
    t = ff.dense(t, 8, name="fc2")
    ff.softmax(t, lbl, name="softmax")
    ex = Executor(ff, optimizer=SGDOptimizer(lr=0.01, momentum=0.9))
    tr = Trainer(ex)
    tr.fit(iterations=2, warmup=1)  # warm the jits once, shared by all legs

    def fit(batches, tel=None):
        try:
            if tel is not None:
                with tel:
                    return tr.fit(iterations=iters, batches=batches,
                                  warmup=1)
            return tr.fit(iterations=iters, batches=batches, warmup=1)
        finally:
            if hasattr(batches, "close"):
                batches.close()

    def stream_batches(source=None, window=rows // 4):
        src = source if source is not None else ArrayStreamSource(arrays)
        return PrefetchLoader(
            iter(StreamingLoader(src, batch, shuffle=True, seed=3,
                                 shuffle_window=window)),
            ex.shard_batch)

    def zc_batches():
        return iter(DeviceResidentLoader(arrays, batch, ex,
                                         shuffle=True, seed=3))

    def host_batches():
        return PrefetchLoader(
            iter(ArrayDataLoader(arrays, batch, shuffle=True, seed=3)),
            ex.shard_batch)

    per_row_s = 1e-4

    def throttled_stream_batches():
        return stream_batches(
            ThrottledSource(ArrayStreamSource(arrays), per_row_s=per_row_s),
            window=batch * 2)

    def inline_throttled_batches():
        src = ThrottledSource(ArrayStreamSource(arrays),
                              per_row_s=per_row_s)
        pos = 0
        while True:
            if pos + batch > rows:
                pos = 0
            yield ex.shard_batch(src.read(pos, pos + batch))
            pos += batch

    # The alternating-order paired protocol lives in
    # obs.compare.paired_measure (shared with measure_telemetry.py);
    # here the statistic is the RATIO form, control = two B legs.
    from flexflow_tpu.obs.compare import paired_measure

    def paired_ratio(name, make_a, make_b, bar):
        """Median over reps of (A samples/s) / (B samples/s), with an
        A/A control run under the same alternating-order pairing."""
        res = paired_measure(
            make_a=lambda r: fit(make_a())["samples_per_s"],
            make_b=lambda r: fit(make_b())["samples_per_s"],
            reps=reps,
            control=lambda r: fit(make_b())["samples_per_s"],
        )
        med, ctl = res.median_ratio, res.median_aa_ratio
        ok = "PASS" if med >= bar else "FAIL"
        print(f"{name:<22} {med:>7.3f}x  (bar >= {bar}x, a_a "
              f"{ctl:.3f}x) {ok}")
        return med >= bar

    print(f"streaming data-plane A/B: median of {reps} paired ratios, "
          f"{iters} iters, batch {batch}, {rows} rows, {nd} devices")
    failures = 0

    # Context row, not an acceptance bar: host ArrayDataLoader tier.
    host = fit(host_batches())["samples_per_s"]
    print(f"{'host+prefetch':<22} {host:>9.1f} samples/s")

    try:
        fit(zc_batches())  # probe the budget before committing to reps
        if not paired_ratio("stream_vs_zc", stream_batches, zc_batches,
                            bar=0.9):
            failures += 1
    except DeviceMemoryError as e:
        print(f"stream_vs_zc skipped: {e}")

    if not paired_ratio("overlap_speedup", throttled_stream_batches,
                        inline_throttled_batches, bar=1.3):
        failures += 1

    # Input-wait audit: JSONL events vs the folded summary, exact
    # (parsed through the ONE log reader, obs.reader.RunLog).
    from flexflow_tpu.obs.reader import RunLog

    with tempfile.TemporaryDirectory(prefix="data_ab_") as d:
        tel = Telemetry(os.path.join(d, "audit"))
        path = tel.path
        stats = fit(throttled_stream_batches(), tel=tel)
        summary = stats.get("telemetry", {})
        events = RunLog.load(path).select("input_wait")
        total = round(sum(e["wall_s"] for e in events), 6)
        n_ok = summary.get("input_waits") == len(events)
        t_ok = summary.get("input_wait_s_total") == total
        ok = "PASS" if (n_ok and t_ok and events) else "FAIL"
        print(f"{'input_wait audit':<22} {len(events)} events, "
              f"sum {total}s == summary "
              f"{summary.get('input_wait_s_total')}s, "
              f"count == {summary.get('input_waits')} {ok}")
        if not (n_ok and t_ok and events):
            failures += 1

    return 1 if failures else 0


def main():
    argv = sys.argv[1:]
    if "--child" in argv:
        argv.remove("--child")
        return child(argv)
    return parent(argv)


if __name__ == "__main__":
    sys.exit(main())
