"""Input-pipeline overlap A/B: Trainer.fit prefetch=0 vs prefetch=2.

VERDICT r4 item 4: the reference overlaps H2D staging with compute
(zero-copy dataset region + in-step gather, ``dlrm.cu:20-50``,
``dlrm.cc:151-156``); ``Trainer.fit`` now double-buffers the host
gather + ``shard_batch`` H2D behind the device step.  This tool
measures the before/after on the live chip with a HOST-RESIDENT
dataset (the expensive per-step host path: native row gather + H2D of
a b=512 f32 image batch ~ 320 MB/step at 229x229).

Runs AlexNet (the headline app) with host arrays through
``ArrayDataLoader``; prints one summary line per arm plus the delta.
Safe through the relay: both arms time 12 fused steps between
host-readback fences (Trainer.fit's protocol).
"""
import sys
import time

import numpy as np


def main():
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.data.loader import ArrayDataLoader, synthetic_arrays
    from flexflow_tpu.models.alexnet import build_alexnet
    from flexflow_tpu.optim import SGDOptimizer
    from flexflow_tpu.runtime.executor import Executor
    from flexflow_tpu.runtime.trainer import Trainer

    import jax

    on_tpu = jax.default_backend() != "cpu"
    batch = 512 if on_tpu else 16
    image = 229 if on_tpu else 64
    iters = 12 if on_tpu else 3
    cfg = FFConfig(batch_size=batch, compute_dtype="bfloat16")
    ff = build_alexnet(batch_size=batch, image_size=image,
                       num_classes=1000, config=cfg)
    ex = Executor(ff, optimizer=SGDOptimizer(lr=0.01, momentum=0.9))
    arrays = synthetic_arrays(ff, num_samples=batch * 8, seed=0,
                              int_high={"label": 1000})

    from flexflow_tpu.data.loader import DeviceResidentLoader

    results = {}
    # ABCABC: host-sync / host-prefetch / device-resident (ZC pattern),
    # interleaved to split drift from effect.
    for arm in ("sync", "prefetch", "device") * 2:
        if arm == "device":
            batches = iter(DeviceResidentLoader(
                arrays, batch, ex, shuffle=True, seed=1))
            # Keep the depth-2 overlap here too: the per-step dispatch
            # chain (idx put + eager takes) would otherwise serialize
            # inside the timed loop while the host arm overlaps, biasing
            # the comparison (shard_batch re-place is a no-op).
            depth = 2
        else:
            batches = iter(ArrayDataLoader(arrays, batch, shuffle=True,
                                           seed=1))
            depth = 2 if arm == "prefetch" else 0
        t0 = time.time()
        stats = Trainer(ex).fit(iterations=iters, batches=batches,
                                warmup=3, prefetch=depth)
        results.setdefault(arm, []).append(stats["samples_per_s"])
        print(f"{arm}: {stats['samples_per_s']:.1f} samples/s "
              f"(wall {time.time()-t0:.1f}s)", flush=True)

    best = {k: max(v) for k, v in results.items()}
    print(f"SUMMARY prefetch_off={best['sync']:.1f} "
          f"prefetch_on={best['prefetch']:.1f} "
          f"device_resident={best['device']:.1f} "
          f"speedup={best['prefetch'] / best['sync']:.3f}x "
          f"zc_speedup={best['device'] / best['sync']:.3f}x "
          f"platform={jax.default_backend()}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
