"""Flash-attention block-size sweep on the live TPU.

Round-2 finding (BASELINE.md / memory): the fwd kernel measured
~14.7 ms at (b16, h8, t2048, hd64) and is NOT MXU-bound (bf16 vs f32
dots changed <5%) — suspected VPU exp + per-block streaming-softmax
correction overhead.  Larger blocks amortize the corrections; this
sweeps FF_FLASH_BLOCK (which pallas_kernels reads at import) in fresh
subprocesses and times fwd and fwd+bwd with relay-safe fencing
(jitted loop, one jax.device_get per measurement, <=20 reps).

Usage: python tools/sweep_flash.py [b h t hd]
"""

import os
import subprocess
import sys

BODY = r"""
import os, sys, time
import jax, jax.numpy as jnp

b, h, t, hd = (int(x) for x in sys.argv[1:5])
from flexflow_tpu.ops import pallas_kernels as pk

shape = (b, h, t, hd)
if not pk.flash_supported(shape, jnp.bfloat16):
    print(f"block {os.environ.get('FF_FLASH_BLOCK')}: unsupported at {shape}")
    sys.exit(0)
# The VMEM cap may shrink the requested block (oversized requests now
# clamp instead of OOMing Mosaic); label the row with what actually ran.
actual = pk._flash_block(t, hd, 2)
if str(actual) != os.environ.get("FF_FLASH_BLOCK", ""):
    print(f"(FF_FLASH_BLOCK={os.environ.get('FF_FLASH_BLOCK')} "
          f"clamped to {actual})")
key = jax.random.PRNGKey(0)
q, k, v = (jax.random.normal(jax.random.fold_in(key, i), shape, jnp.bfloat16)
           for i in range(3))

def loss(q, k, v):
    return jnp.sum(pk.flash_attention(q, k, v, True).astype(jnp.float32))

# All three cotangents summed into a q-shaped carry, so the chain keeps
# BOTH backward kernels (dq and dkv) alive — grad wrt q alone would let
# XLA dead-code-eliminate the dkv pallas_call.
grad_all = jax.grad(loss, argnums=(0, 1, 2))

def bwd_step(x):
    dq, dk, dv = grad_all(x, k, v)
    return (dq + dk + dv).astype(x.dtype)

# Two-point jitted-chain timing (the relay's per-dispatch cost is of
# the same magnitude as the kernel itself, so single calls sit on a
# dispatch floor): one jit'd dependent chain x = f(x) of length N is
# ONE dispatch, and the (N2 - N1) slope isolates per-iteration cost.
# Chains stay short and fenced — a 30-long pallas chain once wedged
# the relay (CLAUDE.md).
def timeit(step, pallas_per_step=1):
    # Cap the dependent pallas-call chain at 24: a 30-long chain once
    # wedged the relay for ~70 min (CLAUDE.md).  bwd_step carries ~3
    # pallas calls (fwd recompute + dq + dkv), so its chain lengths
    # shrink to (2, 8).
    n2 = min(16, max(2, 24 // pallas_per_step))
    n1 = max(1, n2 // 4)
    def chain(n):
        # Min of 3: relay delays are additive one-sided noise (several
        # ms per dispatch), so the min estimates the compute time.
        @jax.jit
        def run(x):
            return jax.lax.fori_loop(0, n, lambda _, x: step(x), x)
        y = run(q)
        jax.device_get(y.ravel()[:1])  # compile+warm fence
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            y = run(q)
            jax.device_get(y.ravel()[:1])
            best = min(best, time.perf_counter() - t0)
        return best
    # Non-positive slope = relay noise swamped the signal; retry once,
    # then flag so nobody tunes a block size from garbage.
    for _ in range(2):
        slope = (chain(n2) - chain(n1)) / (n2 - n1) * 1e3
        if slope > 0:
            return slope
    # stdout, not stderr: the parent sweep drops child stderr whenever
    # stdout is non-empty, and this flag must reach the user.
    print(f"WARNING: non-positive slope {slope:.2f} ms (relay noise); "
          f"treat this row as unreliable", flush=True)
    return float("nan")

fwd_ms = timeit(lambda x: pk.flash_attention(x, k, v, True).astype(x.dtype))
bwd_ms = timeit(bwd_step, pallas_per_step=3)
flops = 4.0 * b * h * t * t * hd / 2  # causal fwd
print(f"block {os.environ.get('FF_FLASH_BLOCK', '128'):>4s}: "
      f"fwd {fwd_ms:7.2f} ms ({flops / (fwd_ms * 1e-3) / 1.97e14 * 100:4.1f}% "
      f"of bf16 peak)  fwd+bwd {bwd_ms:7.2f} ms")
"""


def main():
    shape = sys.argv[1:5] or ["16", "8", "2048", "64"]
    print(f"flash sweep at (b,h,t,hd)={tuple(int(x) for x in shape)}")
    for block in ("128", "256", "512", "1024"):
        env = dict(os.environ, FF_FLASH_BLOCK=block)
        # NO timeout: killing a child mid-TPU-claim wedges the relay
        # tunnel for hours (CLAUDE.md environment hazards).  A wedged
        # config must be waited out or the whole sweep abandoned.
        proc = subprocess.run(
            [sys.executable, "-c", BODY, *shape],
            env=env, capture_output=True, text=True,
        )
        out = proc.stdout.strip() or proc.stderr.strip()[-300:]
        print(out)


if __name__ == "__main__":
    main()
