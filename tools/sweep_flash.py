"""Flash-attention block-size sweep on the live TPU.

Round-2 finding (BASELINE.md / memory): the fwd kernel measured
~14.7 ms at (b16, h8, t2048, hd64) and is NOT MXU-bound (bf16 vs f32
dots changed <5%) — suspected VPU exp + per-block streaming-softmax
correction overhead.  Larger blocks amortize the corrections; this
sweeps FF_FLASH_BLOCK (which pallas_kernels reads at import) in fresh
subprocesses and times fwd and fwd+bwd with relay-safe fencing
(jitted loop, one jax.device_get per measurement, <=20 reps).

Usage: python tools/sweep_flash.py [b h t hd]
"""

import os
import subprocess
import sys

BODY = r"""
import os, sys, time
import jax, jax.numpy as jnp

b, h, t, hd = (int(x) for x in sys.argv[1:5])
from flexflow_tpu.ops import pallas_kernels as pk

shape = (b, h, t, hd)
if not pk.flash_supported(shape, jnp.bfloat16):
    print(f"block {os.environ.get('FF_FLASH_BLOCK')}: unsupported at {shape}")
    sys.exit(0)
# The VMEM cap may shrink the requested block (oversized requests now
# clamp instead of OOMing Mosaic); label the row with what actually ran.
actual = pk._flash_block(t, hd, 2)
if str(actual) != os.environ.get("FF_FLASH_BLOCK", ""):
    print(f"(FF_FLASH_BLOCK={os.environ.get('FF_FLASH_BLOCK')} "
          f"clamped to {actual})")
key = jax.random.PRNGKey(0)
q, k, v = (jax.random.normal(jax.random.fold_in(key, i), shape, jnp.bfloat16)
           for i in range(3))

fwd = jax.jit(lambda q, k, v: pk.flash_attention(q, k, v, True))

def loss(q, k, v):
    return jnp.sum(pk.flash_attention(q, k, v, True).astype(jnp.float32))

bwd = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

def timeit(fn, reps=10):
    out = fn(q, k, v)
    jax.device_get(jax.tree.leaves(out)[0].ravel()[:1])  # compile+warm fence
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(q, k, v)
    jax.device_get(jax.tree.leaves(out)[0].ravel()[:1])
    return (time.perf_counter() - t0) / reps * 1e3

fwd_ms = timeit(fwd)
bwd_ms = timeit(bwd)
flops = 4.0 * b * h * t * t * hd / 2  # causal fwd
print(f"block {os.environ.get('FF_FLASH_BLOCK', '128'):>4s}: "
      f"fwd {fwd_ms:7.2f} ms ({flops / (fwd_ms * 1e-3) / 1.97e14 * 100:4.1f}% "
      f"of bf16 peak)  fwd+bwd {bwd_ms:7.2f} ms")
"""


def main():
    shape = sys.argv[1:5] or ["16", "8", "2048", "64"]
    print(f"flash sweep at (b,h,t,hd)={tuple(int(x) for x in shape)}")
    for block in ("128", "256", "512", "1024"):
        env = dict(os.environ, FF_FLASH_BLOCK=block)
        # NO timeout: killing a child mid-TPU-claim wedges the relay
        # tunnel for hours (CLAUDE.md environment hazards).  A wedged
        # config must be waited out or the whole sweep abandoned.
        proc = subprocess.run(
            [sys.executable, "-c", BODY, *shape],
            env=env, capture_output=True, text=True,
        )
        out = proc.stdout.strip() or proc.stderr.strip()[-300:]
        print(out)


if __name__ == "__main__":
    main()
