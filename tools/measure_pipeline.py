"""Pipeline dispatch-overhead measurement (PIPELINE_OVERHEAD.md rows).

Round 6 (ISSUE 3) additions on top of the round-3/5 table: a CHUNK
sweep (``--pipeline-chunk`` c folds each stage's per-microbatch fwd/bwd
into one scanned program — host programs per step drop from ``2*S*m``
to ``2*S*ceil(m/c)``, printed from the actual ``last_schedule`` event
count) and a SUPERSTEP A/B (k pipeline steps dispatched back-to-back
under ONE ``jax.device_get`` fence, ``Trainer._fit_superstep_pipeline``
semantics timed inline).  Acceptance: S=4 mb=8 c=mb 1f1b beats the
round-5 1f1b number (981 ms) by >= 1.2x on the 8-dev virtual CPU mesh.

Round 7 (ISSUE 5) adds the COMPILED whole-step rows (``--pipeline-
compiled``: the entire multi-stage step as ONE jitted program on the
shared stage mesh, 1 host program per step) and the FUSED pipeline
superstep A/B (``build_superstep(k)``: one dispatch + one fence per k
steps, 1/k programs per step) — both same-day against the unchanged
host path per the round-6 box-drift caveat.  Acceptance: compiled
beats the chunked host path per-step in the dispatch-bound regime
(``--batch 64 --width 256``, S=4 mb=8).

The virtual mesh multiplexes ONE core, so these numbers isolate host
dispatch + boundary transfer cost, exactly as in rounds 3/5.

Usage: python tools/measure_pipeline.py [--width 1024 --batch 512]
"""
import argparse
import os
import sys
import time

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"


def build(batch, width, depth=8, classes=32):
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.graph import FFModel
    import jax.numpy as jnp

    ff = FFModel(FFConfig(batch_size=batch))
    x = ff.create_tensor((batch, width), name="x")
    lbl = ff.create_tensor((batch,), dtype=jnp.int32, name="label")
    t = x
    for i in range(depth):
        t = ff.dense(t, width, activation="relu", name=f"fc{i}")
    t = ff.dense(t, classes, name="head")
    ff.softmax(t, lbl, name="softmax")
    return ff


def time_step(ex, batch, iters=30, warmup=5):
    import jax

    params, opt_state, state = ex.init(seed=0)
    placed = ex.shard_batch(batch)
    for _ in range(warmup):
        params, opt_state, state, m = ex.train_step(
            params, opt_state, state, placed)
    jax.device_get(m)
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, state, m = ex.train_step(
            params, opt_state, state, placed)
    jax.device_get(m)
    return (time.perf_counter() - t0) / iters * 1e3  # ms


def time_superstep(ex, batch, k, iters=32, warmup=4):
    """k steps dispatched back-to-back, ONE device_get of all k
    metrics per superstep — the pipeline-superstep fence pattern."""
    import jax

    params, opt_state, state = ex.init(seed=0)
    placed = ex.shard_batch(batch)
    ms = []
    for _ in range(warmup):
        params, opt_state, state, m = ex.train_step(
            params, opt_state, state, placed)
        ms.append(m)
    jax.device_get(ms)
    t0 = time.perf_counter()
    done = 0
    while done < iters:
        n = min(k, iters - done)
        ms = []
        for _ in range(n):
            params, opt_state, state, m = ex.train_step(
                params, opt_state, state, placed)
            ms.append(m)
        jax.device_get(ms)
        done += n
    return (time.perf_counter() - t0) / iters * 1e3  # ms


def time_fused_superstep(pipe, batch, k, iters=32, warmup=1):
    """k whole pipeline steps as ONE compiled dispatch + ONE fence
    (``PipelineExecutor.build_superstep`` on the compiled-step path)."""
    import jax

    from flexflow_tpu.runtime.trainer import relay_safe_steps

    k = relay_safe_steps(k)
    params, opt_state, state = pipe.init(seed=0)
    fn = pipe.build_superstep(k)
    stacked = pipe.stack_steps([batch] * k)
    for _ in range(warmup):
        params, opt_state, state, ms = fn(params, opt_state, state, stacked)
    jax.device_get(ms)
    t0 = time.perf_counter()
    done = 0
    while done < iters:
        params, opt_state, state, ms = fn(params, opt_state, state, stacked)
        jax.device_get(ms)
        done += k
    return (time.perf_counter() - t0) / done * 1e3  # ms/step


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--width", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--iters", type=int, default=30)
    args = ap.parse_args()

    import jax
    import numpy as np

    from flexflow_tpu.optim import SGDOptimizer
    from flexflow_tpu.parallel.strategy import ParallelConfig, StrategyStore
    from flexflow_tpu.runtime.executor import Executor
    from flexflow_tpu.runtime.pipeline import PipelineExecutor

    nd = len(jax.devices())
    assert nd == 8, f"expected 8 virtual devices, got {nd}"
    ff = build(args.batch, args.width)
    rng = np.random.default_rng(0)
    batch = {
        "x": rng.standard_normal((args.batch, args.width)).astype(np.float32),
        "label": rng.integers(0, 32, size=(args.batch,)).astype(np.int32),
    }
    opt = lambda: SGDOptimizer(lr=0.01, momentum=0.9)

    plain = Executor(ff, strategy=StrategyStore.data_parallel(nd),
                     optimizer=opt())
    t_plain = time_step(plain, batch, args.iters)
    print(f"plain DP x{nd}: {t_plain:.1f} ms", flush=True)

    def pipe_store(S):
        store = StrategyStore(nd)
        per = nd // S
        ops = [f"fc{i}" for i in range(8)] + ["head", "softmax"]
        for i, name in enumerate(ops):
            si = min(i * S // len(ops), S - 1)
            ids = tuple(range(si * per, (si + 1) * per))
            store.set(name, ParallelConfig(n=per, device_ids=ids))
        return store

    def make_pipe(S, mb, sched, c, compiled=False):
        return PipelineExecutor(
            ff, pipe_store(S), optimizer=opt(),
            microbatches=mb, schedule=sched, chunk=c, compiled=compiled,
        )

    for S in (2, 4):
        for mb in (1, 4, 8):
            # Both schedules at c=1 (round-3/5 comparability), then the
            # chunk sweep on 1f1b: c in {2, mb}, then the compiled
            # whole-step row (ONE program; schedule is moot — the
            # trace sequences stages by data dependency).
            chunks = [1] if mb == 1 else [1, 2, mb]
            for sched in ("gpipe", "1f1b"):
                for c in (chunks if sched == "1f1b" else [1]):
                    pipe = make_pipe(S, mb, sched, c)
                    t = time_step(pipe, batch, args.iters)
                    progs = len(pipe.last_schedule)
                    flag = " <= plain" if t <= t_plain else ""
                    print(
                        f"pipeline S={S} mb={mb} c={c} {sched}: "
                        f"{t:.1f} ms  ({progs} programs/step){flag}",
                        flush=True,
                    )
            pipe = make_pipe(S, mb, "1f1b", 1, compiled=True)
            t = time_step(pipe, batch, args.iters)
            flag = " <= plain" if t <= t_plain else ""
            print(
                f"pipeline S={S} mb={mb} compiled: {t:.1f} ms  "
                f"(1 program/step){flag}",
                flush=True,
            )

    # Superstep-over-pipeline A/B: one fence per k=8 steps at the
    # dispatch-minimal chunk (and at c=1 for the fence-only delta),
    # then the FUSED compiled superstep (one dispatch + one fence per
    # k steps — 1/k programs per step).
    for c in (1, 8):
        pipe = make_pipe(4, 8, "1f1b", c)
        t1 = time_superstep(pipe, batch, k=1, iters=args.iters)
        t8 = time_superstep(pipe, batch, k=8, iters=args.iters)
        print(
            f"superstep S=4 mb=8 c={c} 1f1b: k=1 {t1:.1f} ms -> "
            f"k=8 {t8:.1f} ms/step ({t1 / t8:.2f}x)",
            flush=True,
        )
    pipe = make_pipe(4, 8, "1f1b", 1, compiled=True)
    t1 = time_superstep(pipe, batch, k=1, iters=args.iters)
    t8 = time_fused_superstep(pipe, batch, k=8, iters=args.iters)
    print(
        f"superstep S=4 mb=8 compiled: k=1 {t1:.1f} ms -> "
        f"k=8 fused {t8:.1f} ms/step ({t1 / t8:.2f}x)",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
