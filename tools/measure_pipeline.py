"""Pipeline dispatch-overhead measurement (PIPELINE_OVERHEAD.md rows).

VERDICT r4 item 5 acceptance: S=4 mb=4 <= plain-Executor step time at
the b512 x w1024 config.  Reruns the round-3 table configs on the
8-device virtual CPU mesh with the current runtime (1F1B schedule,
batched stage-input device_put, cached zero cotangents) so the before
(round-3 table) / after (this) delta is attributable to the round-5
work.  The virtual mesh multiplexes ONE core, so these numbers isolate
host dispatch + boundary transfer cost, exactly as in round 3.

Usage: python tools/measure_pipeline.py [--width 1024 --batch 512]
"""
import argparse
import os
import sys
import time

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"


def build(batch, width, depth=8, classes=32):
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.graph import FFModel
    import jax.numpy as jnp

    ff = FFModel(FFConfig(batch_size=batch))
    x = ff.create_tensor((batch, width), name="x")
    lbl = ff.create_tensor((batch,), dtype=jnp.int32, name="label")
    t = x
    for i in range(depth):
        t = ff.dense(t, width, activation="relu", name=f"fc{i}")
    t = ff.dense(t, classes, name="head")
    ff.softmax(t, lbl, name="softmax")
    return ff


def time_step(ex, batch, iters=30, warmup=5):
    import jax

    params, opt_state, state = ex.init(seed=0)
    placed = ex.shard_batch(batch)
    for _ in range(warmup):
        params, opt_state, state, m = ex.train_step(
            params, opt_state, state, placed)
    jax.device_get(m)
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, state, m = ex.train_step(
            params, opt_state, state, placed)
    jax.device_get(m)
    return (time.perf_counter() - t0) / iters * 1e3  # ms


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--width", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--iters", type=int, default=30)
    args = ap.parse_args()

    import jax
    import numpy as np

    from flexflow_tpu.optim import SGDOptimizer
    from flexflow_tpu.parallel.strategy import ParallelConfig, StrategyStore
    from flexflow_tpu.runtime.executor import Executor
    from flexflow_tpu.runtime.pipeline import PipelineExecutor

    nd = len(jax.devices())
    assert nd == 8, f"expected 8 virtual devices, got {nd}"
    ff = build(args.batch, args.width)
    rng = np.random.default_rng(0)
    batch = {
        "x": rng.standard_normal((args.batch, args.width)).astype(np.float32),
        "label": rng.integers(0, 32, size=(args.batch,)).astype(np.int32),
    }
    opt = lambda: SGDOptimizer(lr=0.01, momentum=0.9)

    plain = Executor(ff, strategy=StrategyStore.data_parallel(nd),
                     optimizer=opt())
    t_plain = time_step(plain, batch, args.iters)
    print(f"plain DP x{nd}: {t_plain:.1f} ms", flush=True)

    def pipe_store(S):
        store = StrategyStore(nd)
        per = nd // S
        ops = [f"fc{i}" for i in range(8)] + ["head", "softmax"]
        for i, name in enumerate(ops):
            si = min(i * S // len(ops), S - 1)
            ids = tuple(range(si * per, (si + 1) * per))
            store.set(name, ParallelConfig(n=per, device_ids=ids))
        return store

    for S in (2, 4):
        for mb in (1, 4, 8):
            for sched in ("gpipe", "1f1b"):
                pipe = PipelineExecutor(
                    ff, pipe_store(S), optimizer=opt(),
                    microbatches=mb, schedule=sched,
                )
                t = time_step(pipe, batch, args.iters)
                flag = " <= plain" if t <= t_plain else ""
                print(f"pipeline S={S} mb={mb} {sched}: {t:.1f} ms{flag}",
                      flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
