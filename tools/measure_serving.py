#!/usr/bin/env python
"""Serving-scheduler A/B: the SERVING.md "Scheduler policy" acceptance
run on the 8-dev virtual CPU mesh.

The measurements, each against its acceptance bar:

- ``slo_vs_fifo p99``: queue-wait p99 of the SLO-CARRYING class (tier
  0 — the class the policy exists to protect; the global p99 is
  work-conservation-invariant and hides the win) under the slo policy
  (tier+EDF admission, adaptive K, preemption) vs FIFO, same bursty
  overload workload, REAL engine.  Bar: >= 1.3x.
- ``slo attainment``: fraction of finite-SLO requests finishing inside
  their deadline must be STRICTLY higher under the slo policy.
- ``dispatch exactness``: the simulate-mode run (the serve-auto cost
  oracle) must predict the real run's dispatch counts EXACTLY — same
  decision log, same prefill count, same decode-superstep count, and
  the telemetry program counter must equal prefills + supersteps.
- ``spec tokens/dispatch``: decode tokens per decode dispatch under a
  d=12 full self-draft (the degenerate fully-accepting case) vs plain
  fused k=8 on the SAME requests, outputs byte-identical every rep
  (acceptance decides dispatch count, never content — SERVING.md
  "Speculative decoding").  Bar: >= 1.5x.
- ``paged capacity``: under ``FF_DEVICE_MEM_BYTES`` = half the padded
  cache budget, the padded executor must refuse with
  ``DeviceMemoryError``, the budget-sized paged pool must serve
  requests end-to-end, and at a short prompt it must admit >= 2x the
  padded concurrent batch (SERVING.md "Cache layout").
- ``fleet t0 p99``: tier-0 queue-wait p99 of a 2-replica fleet behind
  the least-loaded router vs the single engine, same bursty overload
  (SERVING.md "Fleet"; attainment saturates at 1.0 here and cannot
  differentiate).  Bar: >= 1.3x.
- ``fleet replica loss``: replica 0 dies mid-run with a zero restart
  budget — the fleet must journal-transplant its in-flight requests to
  the survivor with ZERO failed requests, and its SLO attainment must
  be >= the restarting single engine's (max_restarts=1, same fault)
  every rep.
- ``prefix t0 p99`` + ``prefix exactness``: a burst sharing a
  full-block prompt prefix, prefix cache ON vs OFF on the same paged
  pool (SERVING.md "Prefix sharing").  Full hits skip the prefill
  dispatch entirely, so the prefill count must drop and the tier-0
  queue-wait p99 must improve >= 1.3x — at byte-identical outputs
  (sharing changes dispatch count, never content); and sim == real
  dispatch exactness must HOLD with the cache armed (serve-auto
  scores prefix-cache candidates through the same ledger).

All compared metrics are VIRTUAL-clock values (the latency model's
deterministic ms), so the paired protocol's A/A control reads exactly
1.000x — reps vary the workload seed, not the box; the bar measures
the policy, never wall noise.

Usage: env PYTHONPATH=/root/repo python tools/measure_serving.py
       [--reps N]
(re-execs in a clean JAX_PLATFORMS=cpu subprocess with the axon
sitecustomize dropped, per CLAUDE.md.)
"""

import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def parent(argv):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    return subprocess.call(
        [sys.executable, os.path.abspath(__file__), "--child"] + argv,
        env=env,
    )


def _arg(argv, flag, default):
    if flag in argv:
        return int(argv[argv.index(flag) + 1])
    return default


def child(argv):
    os.environ.pop("FF_TELEMETRY_DIR", None)
    import jax

    jax.config.update("jax_platforms", "cpu")

    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.models.transformer import build_transformer_lm
    from flexflow_tpu.obs.compare import paired_measure
    from flexflow_tpu.runtime.serving import ServingExecutor
    from flexflow_tpu.runtime.telemetry import Telemetry
    from flexflow_tpu.serving import (
        ScheduledServer,
        SchedulerPolicy,
        SlotShape,
        WorkloadSpec,
        make_workload,
    )

    reps = _arg(argv, "--reps", 5)
    max_batch, max_seq, buckets = 2, 32, (8,)

    ff = build_transformer_lm(
        batch_size=max_batch, seq_len=max_seq, vocab_size=32,
        d_model=16, num_heads=2, num_layers=1,
        config=FFConfig(batch_size=max_batch),
    )
    sex = ServingExecutor(ff, max_batch=max_batch, max_seq=max_seq,
                          buckets=buckets)
    params, state = sex.init(seed=0)
    slo_pol = SchedulerPolicy(name="slo")
    fifo_pol = SchedulerPolicy.fifo()

    def workload(seed):
        # Bursty overload: 24 requests against 2 slots, 12 per burst,
        # 3 priority tiers, tier-0 SLO 60 virtual ms.
        return make_workload(WorkloadSpec(
            n_requests=24, vocab=32, prompt_len=(3, 6), max_new=(2, 12),
            mean_gap_ms=1.0, burst=12, priorities=3, slo_ms=60.0,
            seed=5 + seed,
        ))

    def pct(vals, p):
        vals = sorted(vals)
        if not vals:
            return 0.0
        return vals[min(len(vals) - 1, int(round(p * (len(vals) - 1))))]

    def run_real(policy, seed, tel=None):
        srv = ScheduledServer(sex, params, state, decode_steps=8,
                              policy=policy)
        reqs = workload(seed)
        tier0 = {r.id for r in reqs if r.priority == 0}
        if tel is not None:
            with tel:
                _, stats = srv.run(reqs)
        else:
            _, stats = srv.run(reqs)
        t0_p99 = pct([srv.last_queue_waits[i] for i in tier0
                      if i in srv.last_queue_waits], 0.99)
        return srv, stats, t0_p99

    print(f"serving scheduler A/B: median of {reps} paired ratios "
          f"(virtual clock, seed varies per rep), 24 reqs / "
          f"{max_batch} slots / burst 12 / 3 tiers / SLO 60 ms")
    failures = 0

    # -- slo_vs_fifo tier-0 queue-wait p99 (bar >= 1.3x) ----------------------
    res = paired_measure(
        make_a=lambda r: run_real(fifo_pol, r)[2],
        make_b=lambda r: run_real(slo_pol, r)[2],
        reps=reps,
        control=lambda r: run_real(fifo_pol, r)[2],
    )
    med, ctl = res.median_ratio, res.median_aa_ratio
    ok = med >= 1.3
    print(f"{'slo_vs_fifo p99':<22} {med:>7.3f}x  (bar >= 1.3x, a_a "
          f"{ctl:.3f}x) {'PASS' if ok else 'FAIL'}")
    if not ok:
        failures += 1

    # -- SLO attainment strictly higher ---------------------------------------
    worst_gap, atts = None, []
    for r in range(reps):
        _, s_slo, _ = run_real(slo_pol, r)
        _, s_fifo, _ = run_real(fifo_pol, r)
        gap = s_slo["slo_attainment"] - s_fifo["slo_attainment"]
        atts.append((s_fifo["slo_attainment"], s_slo["slo_attainment"]))
        worst_gap = gap if worst_gap is None else min(worst_gap, gap)
    ok = worst_gap is not None and worst_gap > 0
    print(f"{'slo attainment':<22} fifo->slo {atts[0][0]:.3f}->"
          f"{atts[0][1]:.3f} (worst gap {worst_gap:+.3f}, bar > 0) "
          f"{'PASS' if ok else 'FAIL'}")
    if not ok:
        failures += 1

    # -- sim-vs-real dispatch exactness ---------------------------------------
    from flexflow_tpu.obs.reader import RunLog

    with tempfile.TemporaryDirectory(prefix="serving_ab_") as d:
        tel = Telemetry(os.path.join(d, "audit"))
        path = tel.path
        real, real_stats, _ = run_real(slo_pol, 0, tel=tel)
        sim = ScheduledServer.simulated(
            SlotShape(max_batch=max_batch, max_seq=max_seq,
                      buckets=buckets),
            decode_steps=8, policy=slo_pol,
        )
        _, sim_stats = sim.run(workload(0))
        dispatches = real_stats["prefills"] + real_stats["decode_supersteps"]
        run_log = RunLog.load(path)
        ev_dispatches = (len(run_log.select("prefill"))
                         + len(run_log.select("decode_superstep")))
        checks = [
            ("decision log", sim.decisions == real.decisions),
            ("prefills", sim_stats["prefills"] == real_stats["prefills"]),
            ("supersteps", sim_stats["decode_supersteps"]
             == real_stats["decode_supersteps"]),
            ("telemetry events", ev_dispatches == dispatches),
        ]
        bad = [n for n, c in checks if not c]
        ok = not bad
        print(f"{'dispatch exactness':<22} sim == real: "
              f"{dispatches} dispatches "
              f"({real_stats['prefills']} prefills + "
              f"{real_stats['decode_supersteps']} supersteps)"
              + (f"; MISMATCH {bad}" if bad else "")
              + f" {'PASS' if ok else 'FAIL'}")
        if not ok:
            failures += 1

    # -- span reconciliation (OBSERVABILITY.md "Reading a request") -----------
    # Every request's span timeline must telescope EXACTLY to its
    # e2e_ms (integer-microsecond equality, no tolerance) in BOTH the
    # real and the simulated loop — any gap is an instrumentation bug.
    from flexflow_tpu.obs import spans as _spans

    def unreconciled(srv):
        tls = _spans.build_timelines(srv.span_events)
        return [i for i in sorted(tls) if not tls[i].reconciled], len(tls)

    bad_real, n_real = unreconciled(real)
    bad_sim, n_sim = unreconciled(sim)
    ok = not bad_real and not bad_sim and n_real > 0 and n_sim == n_real
    print(f"{'span reconciliation':<22} phase sums == e2e for "
          f"{n_real} real + {n_sim} sim requests"
          + (f"; UNRECONCILED real {bad_real} sim {bad_sim}"
             if bad_real or bad_sim else "")
          + f" {'PASS' if ok else 'FAIL'}")
    if not ok:
        failures += 1

    # -- speculation tokens/dispatch (bar >= 1.5x) ----------------------------
    # SERVING.md "Speculative decoding": d=12 full self-draft vs plain
    # fused k=8, same requests (the tiny model is 1 layer, so the
    # self-draft IS the only draft source — fully accepting, so every
    # round emits d+1 = 13 tokens per slot where plain decode caps at
    # k=8).  Tokens per decode dispatch is a deterministic count, so
    # the A/A control reads exactly 1.000x; every rep additionally
    # pins byte-identical outputs across the two engines.
    from flexflow_tpu.runtime.serving import Server, synthetic_requests

    def spec_reqs(seed):
        return synthetic_requests(4, 32, prompt_len=(3, 6),
                                  max_new_tokens=14, seed=21 + seed)

    plain_toks, spec_toks = {}, {}

    def tokens_per_dispatch(speculate, seed, keep=None):
        srv = Server(sex, params, state, decode_steps=8,
                     speculate=speculate)
        results, stats = srv.run(spec_reqs(seed))
        if keep is not None:
            keep[seed] = {r: results[r].tokens for r in results}
        return (stats["tokens"] - stats["prefills"]) / max(
            stats["decode_supersteps"], 1)

    res = paired_measure(
        make_a=lambda r: tokens_per_dispatch(12, r, spec_toks),
        make_b=lambda r: tokens_per_dispatch(0, r, plain_toks),
        reps=reps,
        control=lambda r: tokens_per_dispatch(12, r),
    )
    med, ctl = res.median_ratio, res.median_aa_ratio
    parity = all(spec_toks[s] == plain_toks[s] for s in plain_toks)
    ok = med >= 1.5 and parity
    print(f"{'spec tokens/dispatch':<22} {med:>7.3f}x  (bar >= 1.5x, "
          f"a_a {ctl:.3f}x) outputs "
          f"{'byte-identical' if parity else 'DIVERGED'} "
          f"{'PASS' if ok else 'FAIL'}")
    if not ok:
        failures += 1

    # -- paged capacity under a fixed HBM budget (bar >= 2x) ------------------
    # SERVING.md "Cache layout": half the padded cache budget via
    # FF_DEVICE_MEM_BYTES — the padded executor must REFUSE
    # (DeviceMemoryError before any device_put), the paged pool sized
    # to that budget must serve requests end-to-end, and at a short
    # prompt (plen << max_seq) it must admit >= 2x the padded batch.
    from flexflow_tpu.data.loader import DeviceMemoryError
    from flexflow_tpu.runtime.serving import Server, synthetic_requests

    budget = sex.cache_total_bytes() // 2
    os.environ["FF_DEVICE_MEM_BYTES"] = str(budget)
    try:
        try:
            sex.init_cache()
            padded_refused = False
        except DeviceMemoryError:
            padded_refused = True
        blk = 4
        blocks = budget // (blk * sex._bytes_per_token)
        paged = ServingExecutor(ff, max_batch=max_batch, max_seq=max_seq,
                                buckets=buckets, kv_block=blk,
                                kv_blocks=blocks)
        results, _ = Server(paged, params, state, decode_steps=4).run(
            synthetic_requests(3, 32, prompt_len=(2, 3),
                               max_new_tokens=2, seed=1)
        )
        served = not any(r.error for r in results.values())
        plen, mnew = 2, 1
        cap_padded = sex.max_admissible_batch(budget, plen, mnew)
        cap_paged = paged.max_admissible_batch(budget, plen, mnew)
        ratio = cap_paged / max(cap_padded, 1)
        ok = padded_refused and served and ratio >= 2.0
        print(f"{'paged capacity':<22} budget {budget} B: padded "
              f"{'refused' if padded_refused else 'FIT (?)'}; paged "
              f"({blocks} x {blk}-token blocks) served "
              f"{len(results)} reqs {'clean' if served else 'WITH ERRORS'}; "
              f"admits {cap_paged} vs {cap_padded} slots @ plen {plen} "
              f"({ratio:.1f}x, bar >= 2x) {'PASS' if ok else 'FAIL'}")
        if not ok:
            failures += 1
    finally:
        os.environ.pop("FF_DEVICE_MEM_BYTES", None)

    # -- fleet: 2 replicas vs 1 under the same burst (bar >= 1.3x) ------------
    # SERVING.md "Fleet": the least-loaded router spreads the burst
    # across two replicas, so the tier-0 queue-wait p99 must drop
    # >= 1.3x vs the single engine (slo_attainment saturates at 1.0 on
    # this workload and cannot differentiate).  The faulted sub-leg
    # kills replica 0 mid-run with a ZERO restart budget: the fleet
    # must journal-transplant its in-flight requests to the survivor
    # with no failed requests, and its attainment must be >= the
    # restarting single engine's (max_restarts=1, same fault) every
    # rep.  Fleet executors bucket up to max_seq: redistribution
    # resumes by re-prefilling over prompt ‖ carried, and that whole
    # prefix must fit a pad bucket.
    from flexflow_tpu.runtime.serving import ServingFaultInjector
    from flexflow_tpu.serving import (
        FleetRouter,
        MemoryJournal,
        ServingResilience,
    )

    fl_stacks = []
    for _ in range(2):
        ex_i = ServingExecutor(ff, max_batch=max_batch, max_seq=max_seq,
                               buckets=(8, max_seq))
        p_i, s_i = ex_i.init(seed=0)
        fl_stacks.append((ex_i, p_i, s_i))

    def make_fleet(kill):
        reps_ = []
        for i, (ex_i, p_i, s_i) in enumerate(fl_stacks):
            inj = (ServingFaultInjector(
                engine_raise_at={1: "injected replica death"})
                if kill and i == 0 else None)
            reps_.append(ScheduledServer(
                ex_i, p_i, s_i, decode_steps=8, policy=slo_pol,
                resilience=ServingResilience(max_restarts=0),
                journal=MemoryJournal(), fault_injector=inj))
        return FleetRouter(reps_, router="least-loaded")

    def t0_p99(waits, reqs):
        tier0 = {r.id for r in reqs if r.priority == 0}
        return pct([waits[i] for i in tier0 if i in waits], 0.99)

    def fleet_run(seed, kill=False):
        fleet = make_fleet(kill)
        reqs = workload(seed)
        _, stats = fleet.run(reqs)
        return t0_p99(fleet.last_queue_waits, reqs), stats

    def single_run(seed, kill=False):
        ex0, p0, s0 = fl_stacks[0]
        srv = ScheduledServer(
            ex0, p0, s0, decode_steps=8, policy=slo_pol,
            resilience=ServingResilience(max_restarts=1 if kill else 0),
            journal=MemoryJournal(),
            fault_injector=(ServingFaultInjector(
                engine_raise_at={1: "injected replica death"})
                if kill else None))
        reqs = workload(seed)
        _, stats = srv.run(reqs)
        return t0_p99(srv.last_queue_waits, reqs), stats

    res = paired_measure(
        make_a=lambda r: single_run(r)[0],
        make_b=lambda r: fleet_run(r)[0],
        reps=reps,
        control=lambda r: single_run(r)[0],
    )
    med, ctl = res.median_ratio, res.median_aa_ratio
    ok = med >= 1.3
    print(f"{'fleet t0 p99':<22} {med:>7.3f}x  (2 replicas vs 1, bar "
          f">= 1.3x, a_a {ctl:.3f}x) {'PASS' if ok else 'FAIL'}")
    if not ok:
        failures += 1

    worst_gap, clean, moved = None, True, 0
    first = None
    for r in range(reps):
        _, fl = fleet_run(r, kill=True)
        _, sg = single_run(r, kill=True)
        gap = fl["slo_attainment"] - sg["slo_attainment"]
        worst_gap = gap if worst_gap is None else min(worst_gap, gap)
        clean = clean and fl["failed"] == 0 and fl["dead_replicas"] == 1
        moved += fl["redistributed"]
        if first is None:
            first = (fl["slo_attainment"], sg["slo_attainment"])
    ok = worst_gap is not None and worst_gap >= 0 and clean and moved > 0
    print(f"{'fleet replica loss':<22} attainment fleet-loss "
          f"{first[0]:.3f} vs single-restart {first[1]:.3f} (worst gap "
          f"{worst_gap:+.3f}, bar >= 0; {moved} redistributed, "
          f"{'0 failed' if clean else 'FAILED/NOT-DEAD'}) "
          f"{'PASS' if ok else 'FAIL'}")
    if not ok:
        failures += 1

    # -- prefix sharing: hit-rate leg (bar >= 1.3x on tier-0 p99) -------------
    # SERVING.md "Prefix sharing": every request in the burst carries
    # the same full-block 16-token prompt, so with the cache ON the
    # first admission seeds the index and every later one is a FULL
    # hit — zero prefill dispatch, memoised first token.  The win is
    # the removed prefill_ms per admission under overload; outputs
    # must stay byte-identical (sharing changes dispatch count, never
    # content).
    pfx_buckets = (16, max_seq)

    def pfx_ex(on):
        return ServingExecutor(ff, max_batch=max_batch,
                               max_seq=max_seq, buckets=pfx_buckets,
                               kv_block=8, kv_blocks=9,
                               prefix_cache=on)

    pfx_on, pfx_off = pfx_ex(True), pfx_ex(False)

    def pfx_workload(seed):
        return make_workload(WorkloadSpec(
            n_requests=24, vocab=32, prompt_len=(16, 16),
            max_new=(2, 6), mean_gap_ms=1.0, burst=12, priorities=3,
            slo_ms=60.0, shared_prefix=16, shared_frac=1.0,
            seed=31 + seed))

    pfx_toks = {True: {}, False: {}}
    pfx_stats = {}

    def pfx_run(on, seed):
        srv = ScheduledServer(pfx_on if on else pfx_off, params, state,
                              decode_steps=8, policy=slo_pol)
        reqs = pfx_workload(seed)
        results, stats = srv.run(reqs)
        pfx_toks[on][seed] = {r: results[r].tokens for r in results}
        pfx_stats[(on, seed)] = stats
        return t0_p99(srv.last_queue_waits, reqs)

    res = paired_measure(
        make_a=lambda r: pfx_run(False, r),
        make_b=lambda r: pfx_run(True, r),
        reps=reps,
        control=lambda r: pfx_run(False, r),
    )
    med, ctl = res.median_ratio, res.median_aa_ratio
    parity = all(pfx_toks[True][s] == pfx_toks[False][s]
                 for s in pfx_toks[False])
    fewer = all(pfx_stats[(True, s)]["prefills"]
                < pfx_stats[(False, s)]["prefills"]
                for s in range(reps))
    pf_on, pf_off = pfx_stats[(True, 0)], pfx_stats[(False, 0)]
    ok = med >= 1.3 and parity and fewer
    print(f"{'prefix t0 p99':<22} {med:>7.3f}x  (cache on vs off, bar "
          f">= 1.3x, a_a {ctl:.3f}x) prefills "
          f"{pf_off['prefills']} -> {pf_on['prefills']} (hit rate "
          f"{pf_on['prefix_hit_rate']:.2f}, "
          f"{pf_on['prefill_tokens_saved']} tokens saved), outputs "
          f"{'byte-identical' if parity else 'DIVERGED'} "
          f"{'PASS' if ok else 'FAIL'}")
    if not ok:
        failures += 1

    # -- prefix sharing: sim == real with the cache armed ---------------------
    sim = ScheduledServer.simulated(
        SlotShape(max_batch=max_batch, max_seq=max_seq,
                  buckets=pfx_buckets, kv_block=8, kv_blocks=9,
                  prefix_cache=True),
        decode_steps=8, policy=slo_pol)
    _, sim_st = sim.run(pfx_workload(0))
    real = ScheduledServer(pfx_on, params, state, decode_steps=8,
                           policy=slo_pol)
    with Telemetry(None):
        _, real_st = real.run(pfx_workload(0))
    checks = [
        ("decision log", sim.decisions == real.decisions),
        ("prefills", sim_st["prefills"] == real_st["prefills"]),
        ("prefix hits",
         sim_st["prefix_hits"] == real_st["prefix_hits"]),
        ("supersteps", sim_st["decode_supersteps"]
         == real_st["decode_supersteps"]),
    ]
    bad = [n for n, c in checks if not c]
    ok = not bad and real_st["prefix_hits"] > 0
    print(f"{'prefix exactness':<22} sim == real with cache on: "
          f"{real_st['prefix_hits']} hits, "
          f"{real_st['prefills']} prefills"
          + (f"; MISMATCH {bad}" if bad else "")
          + f" {'PASS' if ok else 'FAIL'}")
    if not ok:
        failures += 1

    return 1 if failures else 0


def main():
    argv = sys.argv[1:]
    if "--child" in argv:
        argv.remove("--child")
        return child(argv)
    return parent(argv)


if __name__ == "__main__":
    sys.exit(main())
