#!/usr/bin/env python
"""Chaos smoke: the resilience fault matrix in a fresh CPU subprocess.

Runs every scenario in ``flexflow_tpu/runtime/chaos.py`` — raised
fault / NaN batch / NaN loss inside a k=8 superstep, SIGTERM
preemption + resume, checkpoint corruption fallback,
kill-between-force-save-phases — each required to finish with a loss
trajectory bit-identical to the unfaulted run — plus the serving
fault scenarios (SERVING.md): fault isolation (NaN logits / raised
exception inside a decode superstep: the faulted request errors out,
surviving slots' sequences byte-identical), overload shedding,
``serving_engine_crash`` (journaled crash recovery: engine-class
fault kills / in-process-restarts the scheduled server, journal
replay resumes byte-identically, padded AND paged) and
``serving_sigterm_drain`` (drain-on-SIGTERM: in-flight work journaled
at the fence, clean exit, resume byte-identical) and
``serving_spec_fault`` (faults inside the speculative draft+verify
round: faulted slots error at the verify fence, survivors
byte-identical to the UNSPECULATED run, padded AND paged) and
``prefix_donor_eviction`` (prefix sharing: the donor of a shared
KV block crashes mid-decode — refcounts keep the block alive, the
content-hash index survives, sharers byte-identical to the unshared
run; padded oracle AND paged cache-off sub-checks; SERVING.md
"Prefix sharing") and
``replica_loss`` (fleet: a replica engine-fault exhausts its restart
budget, the router redistributes its journaled in-flight requests to
the survivor, merged output byte-identical to the single-replica run,
padded AND paged; SERVING.md "Fleet") — and the multi-host world
failures, ``host_loss`` and ``coordinator_loss``, on the live
2-process ``jax.distributed`` rig (RESILIENCE.md "Host loss & elastic
resize": launcher-classified kill, elastic resize / same-world
coordinator restart, post-recovery trajectory bit-identical).
<2 min on the 8-device virtual CPU mesh; never touches the TPU claim
(the child is pinned to ``JAX_PLATFORMS=cpu`` with the axon
sitecustomize dropped from PYTHONPATH, per CLAUDE.md).

Usage: python tools/chaos_smoke.py [scenario ...]
Exit code 0 iff every scenario passed.
"""

import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def parent(argv):
    """Re-exec in a clean CPU subprocess (fresh backend, 8-dev mesh)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO  # drop /root/.axon_site: no TPU relay
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    return subprocess.call(
        [sys.executable, os.path.abspath(__file__), "--child"] + argv,
        env=env,
    )


def child(argv):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from flexflow_tpu.runtime.chaos import SCENARIOS, run_matrix

    names = [a for a in argv if not a.startswith("-")] or None
    if names:
        unknown = set(names) - set(SCENARIOS)
        if unknown:
            print(f"unknown scenarios: {sorted(unknown)} "
                  f"(have: {list(SCENARIOS)})", file=sys.stderr)
            return 2
    import time

    t0 = time.perf_counter()
    failures = n = 0
    with tempfile.TemporaryDirectory(prefix="chaos_smoke_") as root:
        # One run_matrix call per scenario so each row carries its own
        # wall time (the rig baseline cache in chaos.py persists across
        # calls, so the split costs nothing).
        for name in (names or list(SCENARIOS)):
            ts = time.perf_counter()
            results = run_matrix(root, [name])
            dt = time.perf_counter() - ts
            for ok, rname, detail in results:
                print(f"{'PASS' if ok else 'FAIL'}  {rname:<22} "
                      f"{dt:6.1f}s  {detail}")
                failures += 0 if ok else 1
                n += 1
    print(f"chaos matrix: {n - failures}/{n} passed "
          f"in {time.perf_counter() - t0:.1f}s")
    return 1 if failures else 0


def main():
    argv = sys.argv[1:]
    if "--child" in argv:
        argv.remove("--child")
        return child(argv)
    return parent(argv)


if __name__ == "__main__":
    sys.exit(main())
