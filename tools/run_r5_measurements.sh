#!/usr/bin/env bash
# Round-5 live-TPU measurement sequence.  Same discipline as round 4:
# every step is gated by a fresh tunnel probe (a wedged relay hangs
# every new backend init), runs to completion (NEVER timeout-killed),
# and logs into MEASURED_r5/.
#
# Round-5 ordering rationale (VERDICT r4):
#   - headline bench FIRST (item 1: the round artifact must not depend
#     on the tunnel surviving to the end; bench.py now persists its
#     last good TPU result to MEASURED_r5/last_good_tpu_bench.json),
#   - then Mosaic correctness probes (ADVICE r4: the r4 scatter and
#     chunked-flash rewrites have no committed hardware evidence),
#   - then THREE independent guarded flash races (item 2 / weak 1: the
#     "~2 ms fwd" claim needs >=3 independent chain-timed runs),
#   - then backward-kernel chain timing, sweep, LM decomposition,
#   - then ffsim calibration + prefetch A/B (items 3-4).
#
# Usage: bash tools/run_r5_measurements.sh [from_step]
set -u
cd "$(dirname "$0")/.."
OUT="${FF_MEASURED_DIR:-MEASURED_r5}"
mkdir -p "$OUT"
FROM="${1:-1}"

probe() {
  python tools/probe_tpu.py --timeout 120 || {
    echo "tunnel DOWN before step $1 — stopping sequence" | tee -a "$OUT/sequence.log"
    exit 1
  }
}

step() {  # step <n> <name> <cmd...>
  local n="$1" name="$2"; shift 2
  [ "$n" -lt "$FROM" ] && return 0
  probe "$n"
  echo "=== step $n: $name ($(date -u +%FT%TZ))" | tee -a "$OUT/sequence.log"
  "$@" > "$OUT/$name.log" 2>&1
  echo "rc=$? $(date -u +%FT%TZ)" >> "$OUT/$name.log"
  tail -3 "$OUT/$name.log" | sed 's/^/    /'
}

# 1. Full headline bench FIRST: the primary round artifact.  bench.py
# persists the TPU result so a later wedge cannot erase it.
step 1 bench python bench.py

# 2. Mosaic correctness probes (r4 scatter/chunked-flash kernels that
# shipped without hardware evidence + any r5 kernel work).
step 2 probe_kernels python tools/probe_r4_kernels.py

# 3-5. Flash fwd variant races, guarded protocol, three INDEPENDENT
# runs (separate processes, separate compilations).
step 3 flash_variants_a python tools/probe_flash_variants.py 16 8 2048 64 --blocks=256,512
step 4 flash_variants_b python tools/probe_flash_variants.py 16 8 2048 64 --blocks=256,512
step 5 flash_variants_c python tools/probe_flash_variants.py 16 8 2048 64 --blocks=256,512

# 6. Flash bwd kernel chain timing (never individually timed on chip).
step 6 flash_bwd_variants python tools/probe_flash_bwd_variants.py 16 8 2048 64 --blocks=256,512

# 7. Block sweep with the chain-timed protocol (fwd and fwd+bwd).
step 7 sweep_flash python tools/sweep_flash.py

# 8. Transformer step decomposition (layer slope + remat + chunk race).
step 8 lm_decomp python tools/profile_lm_decomp.py

# 9. Fused-step race: production flash dispatch vs the streamed
# formulation (FF_FLASH_STREAMED) — the promotion gate for v6_stream.
step 9 streamed_step python tools/race_streamed_step.py

# 10. ffsim calibration: measured fused-step vs simulated makespan
# (VERDICT item 3 — anchors the *_speedup_sim numbers).
step 10 calibrate bash -c 'if [ -f tools/calibrate_ffsim.py ]; then python tools/calibrate_ffsim.py; else echo "calibrate_ffsim.py not present yet"; fi'

# 11. Input-prefetch A/B/C (VERDICT item 4 — host/ZC overlap).
step 11 prefetch_ab bash -c 'if [ -f tools/measure_prefetch.py ]; then python tools/measure_prefetch.py; else echo "measure_prefetch.py not present yet"; fi'

# 12. XProf device-plane op breakdown of the fused train step.
step 12 lm_trace python tools/profile_lm_trace.py "$OUT/lm_trace_dir"

# 13. Measured-mode strategy search artifact.
step 13 search_measured python -m flexflow_tpu.search --model alexnet -b 256 \
  --devices 4 --measured -o "$OUT/alexnet_strategy_measured.json"

echo "sequence complete $(date -u +%FT%TZ)" | tee -a "$OUT/sequence.log"
