"""XProf op-level breakdown of the transformer-LM train step.

Captures a trace of a few fused train steps on the live backend, then
parses the XPlane proto with ``jax.profiler.ProfileData`` and prints
the top device ops by total self time — the precise version of the
layer-count decomposition in ``profile_lm_decomp.py`` (per-op timing
through the relay is dispatch-dominated; the trace sees device-side
truth).

Usage: python tools/profile_lm_trace.py [outdir]
"""

import collections
import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def capture(outdir: str) -> None:
    import jax

    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.models.transformer import build_transformer_lm
    from flexflow_tpu.optim import AdamOptimizer
    from flexflow_tpu.runtime.executor import Executor
    from flexflow_tpu.runtime.trainer import Trainer

    smoke = os.environ.get("FF_TRACE_SMOKE") == "1"
    batch, seq, vocab, d, L = ((4, 128, 512, 64, 2) if smoke
                               else (16, 2048, 32768, 512, 6))
    ff = build_transformer_lm(
        batch_size=batch, seq_len=seq, vocab_size=vocab, d_model=d,
        num_heads=8, num_layers=L,
        config=FFConfig(batch_size=batch, compute_dtype="bfloat16"),
    )
    ex = Executor(ff, optimizer=AdamOptimizer(lr=1e-4),
                  devices=jax.devices()[:1])
    tr = Trainer(ex)
    tr.fit(iterations=3, warmup=1)          # compile outside the trace
    jax.profiler.start_trace(outdir)
    tr.fit(iterations=3, warmup=0)
    jax.profiler.stop_trace()


def report(outdir: str, top: int = 25) -> None:
    from jax.profiler import ProfileData

    paths = sorted(glob.glob(os.path.join(outdir, "**", "*.xplane.pb"),
                             recursive=True))
    if not paths:
        print(f"no .xplane.pb under {outdir}", file=sys.stderr)
        return
    data = ProfileData.from_file(paths[-1])

    def plane_totals(plane):
        totals = collections.Counter()
        for line in plane.lines:
            for ev in line.events:
                totals[ev.name] += ev.duration_ns
        return totals

    # Device planes carry the accelerator truth; the host plane's
    # python events double-count.  Fall back to the busiest plane when
    # the backend exposes no device plane (CPU smoke runs).
    planes = list(data.planes)
    device = [p for p in planes
              if "TPU" in p.name or "GPU" in p.name
              or "/device" in p.name.lower()]
    chosen = device or sorted(
        planes, key=lambda p: sum(plane_totals(p).values()), reverse=True)[:1]
    for plane in chosen:
        totals = plane_totals(plane)
        if not totals:
            continue
        whole = sum(totals.values())
        tag = "" if device else "  [host fallback: no device plane]"
        print(f"== plane: {plane.name}{tag}  (sum {whole / 1e6:.1f} ms over "
              f"{len(totals)} op names)")
        for name, ns in totals.most_common(top):
            print(f"  {ns / 1e6:9.3f} ms  {ns / whole * 100:5.1f}%  "
                  f"{name[:110]}")


def main():
    outdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/ff_lm_trace"
    capture(outdir)
    report(outdir)


if __name__ == "__main__":
    main()
