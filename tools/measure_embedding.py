#!/usr/bin/env python
"""Sharded-embedding acceptance A/B (ISSUE 20, SHARDING.md) on the
8-dev virtual CPU mesh.

Three measurements, each against its acceptance bar:

- ``capacity``: under an ``FF_DEVICE_MEM_BYTES`` budget sized so the
  REPLICATED table refuses (``DeviceMemoryError`` naming
  ``--shard-embeddings``), the c=4 row-sharded layout must admit AND
  train.  The doubling probe then reports max admitted vocab per
  layout; bar: sharded >= 2x replicated (the per-device table shrinks
  by c, so c=4 lands at ~4x up to probe granularity).
- ``sharded_vs_replicated``: paired throughput ratio at a vocab both
  layouts hold — a context bar at >= 0.5x (sharding trades bounded
  gather/psum overhead for unbounded vocab; on the relay the combine
  is in-program, not an extra dispatch).
- ``overlap_speedup``: the id-heavy model fed by the streaming reader
  + H2D prefetch vs unprefetched inline reads, both on the SAME
  per-row throttled source (measure_data.py's protocol).  Bar:
  >= 1.3x — id staging must hide behind compute, the property the
  ids-first ``stack_steps`` ordering extends to the fused-superstep
  queue.

The statistic is the paired-median protocol from
``obs.compare.paired_measure`` (alternating order, median of per-pair
ratios, A/A control column) — CPU wall noise at these sizes swings
more than the effects measured.

Usage: env PYTHONPATH=/root/repo python tools/measure_embedding.py
       [--reps N] [--iters N] [--tpu]
(CPU runs re-exec in a clean JAX_PLATFORMS=cpu subprocess with the
axon sitecustomize dropped, per CLAUDE.md; --tpu keeps the relay on
PYTHONPATH and runs on the live chip.)
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def parent(argv):
    env = dict(os.environ)
    if "--tpu" in argv:
        env["PYTHONPATH"] = "/root/.axon_site:" + REPO
    else:
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    return subprocess.call(
        [sys.executable, os.path.abspath(__file__), "--child"] + argv,
        env=env,
    )


def _arg(argv, flag, default):
    if flag in argv:
        return int(argv[argv.index(flag) + 1])
    return default


def child(argv):
    os.environ.pop("FF_TELEMETRY_DIR", None)
    import jax

    if "--tpu" not in argv:
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.data.loader import (
        DeviceMemoryError,
        DeviceResidentLoader,
        PrefetchLoader,
    )
    from flexflow_tpu.data.stream import (
        ArrayStreamSource,
        StreamingLoader,
        ThrottledSource,
    )
    from flexflow_tpu.graph import FFModel
    from flexflow_tpu.obs.compare import paired_measure
    from flexflow_tpu.optim import SGDOptimizer
    from flexflow_tpu.parallel.strategy import ParallelConfig, StrategyStore
    from flexflow_tpu.runtime.executor import Executor
    from flexflow_tpu.runtime.trainer import Trainer

    reps = _arg(argv, "--reps", 9)
    iters = _arg(argv, "--iters", 48)
    batch, bag, d_emb = 32, 4, 16
    rows = batch * 8
    nd = len(jax.devices())

    rng = np.random.default_rng(13)

    def arrays(vocab):
        return {
            "ids": rng.integers(0, vocab, size=(rows, bag)).astype(np.int32),
            "label": rng.integers(0, 8, size=(rows,)).astype(np.int32),
        }

    def executor(vocab, c):
        ff = FFModel(FFConfig(batch_size=batch, seed=7,
                              shard_embeddings=c > 1))
        ids = ff.create_tensor((batch, bag), dtype=np.int32, name="ids")
        lbl = ff.create_tensor((batch,), dtype=np.int32, name="label")
        t = ff.embedding(ids, vocab, d_emb, aggr="sum", name="emb")
        t = ff.dense(t, 8, name="head")
        ff.softmax(t, lbl, name="softmax")
        store = StrategyStore(nd)
        if c > 1:
            store.set("emb", ParallelConfig(n=nd // c, c=c))
        return Executor(ff, strategy=store,
                        optimizer=SGDOptimizer(lr=0.01))

    failures = 0
    print(f"sharded-embedding A/B: median of {reps} paired ratios, "
          f"{iters} iters, batch {batch}, bag {bag}, {nd} devices")

    # -- capacity: the budget where replicated refuses ----------------
    budget = 72 * 1024
    big_vocab = 2048  # table 128 KiB replicated, 32 KiB/device at c=4
    os.environ["FF_DEVICE_MEM_BYTES"] = str(budget)
    try:
        data = arrays(big_vocab)
        try:
            DeviceResidentLoader(data, batch, executor(big_vocab, 1),
                                 shuffle=True, seed=3)
            print(f"{'capacity':<22} replicated vocab={big_vocab} "
                  f"unexpectedly admitted FAIL")
            failures += 1
        except DeviceMemoryError as e:
            assert "--shard-embeddings" in str(e), e
            ex = executor(big_vocab, 4)
            batches = iter(DeviceResidentLoader(data, batch, ex,
                                                shuffle=True, seed=3))
            stats = Trainer(ex).fit(iterations=8, batches=batches,
                                    warmup=1)
            ok = np.isfinite(stats["loss"])
            print(f"{'capacity':<22} vocab={big_vocab}: replicated "
                  f"refused, c=4 trained (loss {stats['loss']:.4f}) "
                  f"{'PASS' if ok else 'FAIL'}")
            if not ok:
                failures += 1

        def admits(vocab, c):
            try:
                DeviceResidentLoader(arrays(vocab), batch,
                                     executor(vocab, c),
                                     shuffle=True, seed=3)
                return True
            except DeviceMemoryError:
                return False

        def max_vocab(c):
            v, probe = 0, 128
            while probe <= (1 << 20) and admits(probe, c):
                v, probe = probe, probe * 2
            return v

        rep, shd = max_vocab(1), max_vocab(4)
        ratio = shd / rep if rep else float("inf")
        ok = ratio >= 2.0
        print(f"{'max_vocab':<22} replicated {rep}, sharded c=4 {shd} "
              f"({ratio:.1f}x, bar >= 2x) {'PASS' if ok else 'FAIL'}")
        if not ok:
            failures += 1
    finally:
        os.environ.pop("FF_DEVICE_MEM_BYTES", None)

    # -- paired throughput + overlap legs -----------------------------
    common_vocab = 512
    data = arrays(common_vocab)

    def fit(ex, batches):
        try:
            return Trainer(ex).fit(iterations=iters, batches=batches,
                                   warmup=1)
        finally:
            if hasattr(batches, "close"):
                batches.close()

    ex_rep = executor(common_vocab, 1)
    ex_shd = executor(common_vocab, 4)
    for ex in (ex_rep, ex_shd):  # warm the jits, shared by all reps
        fit(ex, iter(DeviceResidentLoader(data, batch, ex,
                                          shuffle=True, seed=3)))

    def sps(ex):
        return fit(ex, iter(DeviceResidentLoader(
            data, batch, ex, shuffle=True, seed=3)))["samples_per_s"]

    def paired_ratio(name, a, b, bar):
        res = paired_measure(
            make_a=lambda r: a(),
            make_b=lambda r: b(),
            reps=reps,
            control=lambda r: b(),
        )
        med, ctl = res.median_ratio, res.median_aa_ratio
        ok = "PASS" if med >= bar else "FAIL"
        print(f"{name:<22} {med:>7.3f}x  (bar >= {bar}x, a_a "
              f"{ctl:.3f}x) {ok}")
        return med >= bar

    if not paired_ratio("sharded_vs_replicated",
                        lambda: sps(ex_shd), lambda: sps(ex_rep),
                        bar=0.5):
        failures += 1

    # -- throttled H2D overlap (measure_data protocol, id-heavy) ------
    per_row_s = 1e-4

    def stream_batches():
        src = ThrottledSource(ArrayStreamSource(data),
                              per_row_s=per_row_s)
        return PrefetchLoader(
            iter(StreamingLoader(src, batch, shuffle=True, seed=3,
                                 shuffle_window=batch * 2)),
            ex_rep.shard_batch)

    def inline_batches():
        src = ThrottledSource(ArrayStreamSource(data),
                              per_row_s=per_row_s)
        pos = 0
        while True:
            if pos + batch > rows:
                pos = 0
            yield ex_rep.shard_batch(src.read(pos, pos + batch))
            pos += batch

    if not paired_ratio(
            "overlap_speedup",
            lambda: fit(ex_rep, stream_batches())["samples_per_s"],
            lambda: fit(ex_rep, inline_batches())["samples_per_s"],
            bar=1.3):
        failures += 1

    return 1 if failures else 0


def main():
    argv = sys.argv[1:]
    if "--child" in argv:
        argv.remove("--child")
        return child(argv)
    return parent(argv)


if __name__ == "__main__":
    sys.exit(main())
