"""TPU tunnel health probe with a persistent, committable log.

The axon relay wedges for hours at a time (every ``jax.devices()`` in
a fresh process hangs); the only safe check is a subprocess under a
hard timeout.  Each probe appends one line to
``MEASURED_r5/probe_log.txt`` so the round's artifact trail shows
exactly when the tunnel was up — or that it never was (VERDICT r3
item 1: the evidence that measurement couldn't happen is itself the
artifact).

Usage: ``python tools/probe_tpu.py [--timeout 150]``
Exit code 0 = TPU reachable, 1 = not.
"""
import argparse
import datetime
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(
    REPO, os.environ.get("FF_MEASURED_DIR", "MEASURED_r5"), "probe_log.txt"
)


def probe(timeout_s: float) -> tuple:
    """(ok, detail) — runs jax.devices() in a throwaway subprocess."""
    code = (
        "import jax; d = jax.devices(); "
        "print('PLATFORM=' + jax.default_backend(), len(d))"
    )
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    t0 = time.time()
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            # The SANCTIONED timeout-kill: this throwaway probe exists
            # precisely so nothing else ever needs one (CLAUDE.md:
            # "probe health in a short subprocess first"); killing it
            # abandons a claim attempt, not a held claim.
            capture_output=True, text=True, timeout=timeout_s, env=env,  # fflint: disable=FF007
        )
    except subprocess.TimeoutExpired:
        return False, f"timeout after {timeout_s:.0f}s (backend hang)"
    dt = time.time() - t0
    if out.returncode == 0 and "PLATFORM=" in out.stdout:
        fields = out.stdout.split("PLATFORM=")[1].split()
        if fields[0] != "cpu":
            return True, f"{fields[0]} x{fields[1]} in {dt:.1f}s"
        return False, f"probe fell back to cpu in {dt:.1f}s"
    return False, f"rc={out.returncode}: {out.stderr.strip()[-200:]}"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--timeout", type=float, default=150.0)
    args = ap.parse_args(argv)
    ok, detail = probe(args.timeout)
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ"
    )
    os.makedirs(os.path.dirname(LOG), exist_ok=True)
    with open(LOG, "a") as f:
        f.write(f"{stamp} {'UP' if ok else 'DOWN'} {detail}\n")
    print(f"{stamp} {'UP' if ok else 'DOWN'} {detail}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
