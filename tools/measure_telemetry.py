#!/usr/bin/env python
"""Telemetry overhead A/B: enabled-vs-off per-step cost on the 8-dev
virtual CPU mesh (the OBSERVABILITY.md acceptance table; bar < 2%).

Each regime trains the dispatch-bound MLP twice — telemetry OFF, then
telemetry ON writing a real JSONL stream to a temp dir (the honest
cost: event serialization + flush + heartbeat touch per step/fence) —
and reports ms/step for both plus the overhead.  Regimes:

- ``k1``: the per-step loop (one `step` event + heartbeat per step;
  the unfenced regime, so wall times are dispatch times).
- ``k8``: fused supersteps (`superstep` + 8 `step` events per fence).
- ``pipeline``: S=2 x mb=4 c=4 layer-wise (adds the programs/step
  counter fold per step).
- ``sched_serving``: the SLO scheduler's real-engine loop (request
  lifecycle events incl. the per-superstep ``slots`` occupancy field
  — OBSERVABILITY.md "Reading a request"; row is ms/RUN, one bursty
  24-request workload per leg).

CPU wall noise at these sizes is a few percent between *identical*
runs AND drifts over a session (an A/A test on this box reads 1-15%
"overhead" from ordering alone), so the protocol is paired: each rep
runs the two variants back to back (order alternating between reps)
and the statistic is the MEDIAN OF PER-PAIR RELATIVE DELTAS — drift
cancels to first order inside a pair, and the median rejects the
box's occasional 2x outlier runs.  An ``a_a_pct`` control column runs
the same protocol on two OFF variants; read the overhead against it.

Usage: env PYTHONPATH=/root/repo python tools/measure_telemetry.py
       [--reps N] [--iters N] [--tpu]
(CPU runs re-exec in a clean JAX_PLATFORMS=cpu subprocess with the
axon sitecustomize dropped, per CLAUDE.md; --tpu keeps the relay on
PYTHONPATH and runs on the live chip.)
"""

import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def parent(argv):
    env = dict(os.environ)
    if "--tpu" in argv:
        env["PYTHONPATH"] = "/root/.axon_site:" + REPO
    else:
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    return subprocess.call(
        [sys.executable, os.path.abspath(__file__), "--child"] + argv,
        env=env,
    )


def _arg(argv, flag, default):
    if flag in argv:
        return int(argv[argv.index(flag) + 1])
    return default


def child(argv):
    # The off legs must be genuinely off: FF_TELEMETRY_DIR (e.g. a
    # tpu_watcher.sh environment) would install file-backed telemetry
    # on them via Trainer.fit's maybe_run and corrupt the A/B.
    os.environ.pop("FF_TELEMETRY_DIR", None)
    import jax

    if "--tpu" not in argv:
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.graph import FFModel
    from flexflow_tpu.optim import SGDOptimizer
    from flexflow_tpu.parallel.strategy import ParallelConfig, StrategyStore
    from flexflow_tpu.runtime.executor import Executor
    from flexflow_tpu.runtime.pipeline import PipelineExecutor
    from flexflow_tpu.runtime.telemetry import Telemetry
    from flexflow_tpu.runtime.trainer import Trainer

    reps = _arg(argv, "--reps", 9)
    iters = _arg(argv, "--iters", 256)
    batch, width = 32, 64
    nd = len(jax.devices())

    def mlp():
        ff = FFModel(FFConfig(batch_size=batch, seed=7))
        x = ff.create_tensor((batch, width), name="x")
        lbl = ff.create_tensor((batch,), dtype=np.int32, name="label")
        t = ff.dense(x, width, activation="relu", name="fc1")
        t = ff.dense(t, 8, name="fc2")
        ff.softmax(t, lbl, name="softmax")
        return ff

    # ONE executor (= one set of compiled programs) per regime, warmed
    # before timing, shared by the off and on legs: rebuilding and
    # re-jitting per rep was measured to swamp the telemetry cost by
    # an order of magnitude (allocator/compile-cache churn).
    def full_mesh(k):
        ex = Executor(mlp(), optimizer=SGDOptimizer(lr=0.01, momentum=0.9))
        tr = Trainer(ex)
        tr.fit(iterations=2 * k, warmup=k, steps_per_call=k)  # warm jits

        def run(tel_dir):
            if tel_dir is None:
                return tr.fit(iterations=iters, warmup=1, steps_per_call=k)
            with Telemetry(tel_dir, stall_deadline_s=300.0):
                return tr.fit(iterations=iters, warmup=1, steps_per_call=k)
        return run

    def pipeline():
        ff = FFModel(FFConfig(batch_size=batch, seed=7))
        x = ff.create_tensor((batch, width), name="x")
        lbl = ff.create_tensor((batch,), dtype=np.int32, name="label")
        t = ff.dense(x, width, activation="relu", name="fc0")
        t = ff.dense(t, 8, name="head")
        ff.softmax(t, lbl, name="softmax")
        per = nd // 2
        st = StrategyStore(nd)
        st.set("fc0", ParallelConfig(n=per, device_ids=tuple(range(per))))
        for name in ("head", "softmax"):
            st.set(name, ParallelConfig(
                n=per, device_ids=tuple(range(per, 2 * per))))
        pipe = PipelineExecutor(
            ff, st, optimizer=SGDOptimizer(lr=0.01, momentum=0.9),
            microbatches=4, chunk=4,
        )
        tr = Trainer(pipe)
        tr.fit(iterations=2, warmup=1)  # warm jits

        def run(tel_dir):
            if tel_dir is None:
                return tr.fit(iterations=iters, warmup=1)
            with Telemetry(tel_dir, stall_deadline_s=300.0):
                return tr.fit(iterations=iters, warmup=1)
        return run

    def sched_serving():
        # The serving scheduler's real-engine loop: telemetry ON adds
        # the request-lifecycle events (request_start/prefill/
        # sched_decision+slots/decode_superstep/request_end) — the
        # span-layer instrumentation measured under the same < 2% bar.
        from flexflow_tpu.models.transformer import build_transformer_lm
        from flexflow_tpu.runtime.serving import ServingExecutor
        from flexflow_tpu.serving import (
            ScheduledServer,
            SchedulerPolicy,
            WorkloadSpec,
            make_workload,
        )

        # Sized so one decode superstep carries real compute on the
        # CPU mesh (~ms-scale dispatches): the per-dispatch event cost
        # is fixed (~3 emits + a heartbeat touch), so a toy model
        # would over-weight it 30x vs the ~16 ms relay dispatch the
        # bar is calibrated against.
        max_batch, max_seq = 2, 64
        ffs = build_transformer_lm(
            batch_size=max_batch, seq_len=max_seq, vocab_size=64,
            d_model=64, num_heads=4, num_layers=2,
            config=FFConfig(batch_size=max_batch),
        )
        sexm = ServingExecutor(ffs, max_batch=max_batch,
                               max_seq=max_seq, buckets=(8,))
        p, s = sexm.init(seed=0)
        srv = ScheduledServer(sexm, p, s, decode_steps=8,
                              policy=SchedulerPolicy(name="slo"))

        def reqs():
            return make_workload(WorkloadSpec(
                n_requests=24, vocab=64, prompt_len=(3, 6),
                max_new=(2, 12), mean_gap_ms=1.0, burst=12,
                priorities=3, slo_ms=60.0, seed=13,
            ))

        srv.run(reqs())  # warm jits

        def run(tel_dir):
            if tel_dir is None:
                _, stats = srv.run(reqs())
            else:
                with Telemetry(tel_dir):
                    # First telemetered run pays the one-time
                    # program_cost attribution (Lowered.cost_analysis
                    # is deduped PER TELEMETRY instance, ~1 ms/program
                    # lowering) — a documented first-build cost, like
                    # jit warmup.  The row measures the steady state:
                    # the per-event serialization incl. `slots`.
                    srv.run(reqs())
                    _, stats = srv.run(reqs())
            return stats
        return run

    regimes = [("k1", full_mesh(1), iters), ("k8", full_mesh(8), iters)]
    if nd >= 2:
        regimes.append(("pipeline", pipeline(), iters))
    else:
        print(f"pipeline regime skipped: {nd} device(s)", file=sys.stderr)
    # Serving row normalizes per RUN, not per step (one workload = one
    # "iteration"); the overhead % is normalization-free either way.
    regimes.append(("sched_serving", sched_serving(), 1))

    # The paired-median + A/A-control protocol now lives in
    # obs.compare.paired_measure (this tool's local copy, promoted);
    # ``a`` is the OFF leg, ``b`` the ON leg, the control runs two OFF
    # legs under the same alternation.
    from flexflow_tpu.obs.compare import paired_measure

    print(f"{'regime':<14} {'off ms/step':>12} {'on ms/step':>12} "
          f"{'overhead':>9} {'a_a_pct':>8}   (median of {reps} paired "
          f"A/B deltas, {iters} iters, {nd} devices; "
          f"sched_serving row is ms/run)")
    for name, run, norm in regimes:
        with tempfile.TemporaryDirectory(prefix="tel_ab_") as d:
            res = paired_measure(
                make_a=lambda r, run=run, norm=norm:
                    run(None)["elapsed_s"] / norm * 1e3,
                make_b=lambda r, run=run, norm=norm, name=name: run(
                    os.path.join(d, f"{name}_{r}")
                )["elapsed_s"] / norm * 1e3,
                reps=reps,
                control=lambda r, run=run, norm=norm:
                    run(None)["elapsed_s"] / norm * 1e3,
            )
        print(f"{name:<14} {res.median_a:>12.3f} "
              f"{res.median_b:>12.3f} "
              f"{res.median_delta_pct:>8.2f}% "
              f"{res.median_aa_pct:>7.2f}%")

    # Deterministic accounting: this box's A/B wall clock swings more
    # between identical sessions than the cost being measured, so the
    # primary number is the added per-step host work itself — a tight
    # loop over the exact file-backed calls the instrumented loops
    # make, immune to scheduler noise.  Overhead = this / step time.
    with tempfile.TemporaryDirectory(prefix="tel_micro_") as d:
        tel = Telemetry(os.path.join(d, "micro"))
        N = 20000
        t0 = time.perf_counter()
        for i in range(N):
            tel.record_step(i, loss=1.5, wall_s=0.001)
        us = (time.perf_counter() - t0) / N * 1e6
        t0 = time.perf_counter()
        for i in range(N):
            tel.emit("superstep", k=8, mode="fused", wall_s=0.004,
                     first_step=i)
        emit_us = (time.perf_counter() - t0) / N * 1e6
        t0 = time.perf_counter()
        for i in range(N):
            tel.emit("sched_decision", vclock_ms=float(i),
                     admitted=[i], k=8, slots=[0, 1, 2, 3])
        slots_us = (time.perf_counter() - t0) / N * 1e6
        tel.close()
    print(f"deterministic: record_step+heartbeat = {us:.1f} us/step, "
          f"generic emit = {emit_us:.1f} us, "
          f"sched_decision+slots = {slots_us:.1f} us "
          f"(k1 adds 1 record_step/step; k8 adds 8 record_steps + "
          f"2 emits per 8-step superstep; a serving decode dispatch "
          f"adds ~3 emits incl. the slots occupancy list — "
          f"vs the ~16 ms relay dispatch floor that is well under "
          f"the 2% bar)")
    return 0


def main():
    argv = sys.argv[1:]
    if "--child" in argv:
        argv.remove("--child")
        return child(argv)
    return parent(argv)


if __name__ == "__main__":
    sys.exit(main())
