"""Shared scaffolding for the live-TPU kernel probes.

The two-point jitted-chain slope timer here is the load-bearing
measurement methodology for every round-4 kernel number
(MEASURED_r4/README.md): per-call timings through the relay sit on a
multi-ms dispatch floor, so a probe times ONE dispatch of an N-long
dependent chain, min-of-3 per chain length (relay delays are one-sided
additive noise), and reports the (N2-N1) slope, retrying once and
emitting NaN when noise still swamps the signal.
"""

import sys
import time

import jax


def parse_dims_blocks(argv, default_dims=(16, 8, 2048, 64),
                      default_blocks=(256, 512)):
    """``[b h t hd] [--blocks 256,512]`` with both flag forms; unknown
    flags are an error (a typo must not silently measure defaults)."""
    blocks = list(default_blocks)
    rest = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a.startswith("--blocks"):
            if "=" in a:
                val = a.split("=", 1)[1]
            elif i + 1 < len(argv):
                i += 1
                val = argv[i]
            else:
                sys.exit("--blocks expects a comma-separated list")
            blocks = [int(x) for x in val.split(",")]
        elif a.startswith("--"):
            sys.exit(f"unknown flag {a!r} (only --blocks is supported)")
        else:
            rest.append(a)
        i += 1
    if rest and len(rest) != 4:
        sys.exit(f"expected 4 positional dims (b h t hd), got {rest}")
    dims = tuple(int(x) for x in rest) if len(rest) == 4 else default_dims
    return dims, blocks


def chain_slope_ms(make_run, x0, n1, n2, reps=3):
    """Per-iteration ms from the slope between two chain lengths.

    ``make_run(n)`` returns a jitted callable of one argument that
    executes n dependent iterations; x0 seeds the chain.  Retries once
    on a non-positive slope, then returns NaN rather than garbage.
    """
    def timed(n):
        run = make_run(n)
        y = run(x0)
        jax.device_get(y.ravel()[:1])  # compile+warm fence
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            y = run(x0)
            jax.device_get(y.ravel()[:1])
            best = min(best, time.perf_counter() - t0)
        return best

    for _ in range(2):
        ms = (timed(n2) - timed(n1)) / (n2 - n1) * 1e3
        if ms > 0:
            return ms
    return float("nan")
