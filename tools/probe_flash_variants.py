"""Round-4 flash-forward kernel variants, raced on the live chip.

The round-3/4 sweeps put the production flash forward at 2.4-4.8% of
bf16 peak with a strong block-size dependence — evidence the per-block
VPU work (width-1 lane broadcasts of m/l, streaming corrections,
cross-lane reduces), not the raw exp count, is the ceiling.  Each
variant below isolates one remedy; the winner gets folded into
``ops/pallas_kernels.py``:

  v1_base     the production streaming kernel (control)
  v2_lanes    m/l carried at 128-lane width; subtract via jnp.tile
              (the lane-broadcast idiom from the public JAX TPU flash
              kernel, flash_attention.py:439-453)
  v3_twopass  s staged in a VMEM scratch; pass 1 dots+rowmax only,
              pass 2 exp+sum+p@v — no streaming corrections at all
  v4_fullrow  single-step softmax over the whole (masked) row; trades
              2x dot/exp flops above the diagonal for zero streaming
              machinery and one reduce per row

Usage (fresh subprocess per variant; relay-safe fencing):
    python tools/probe_flash_variants.py [b h t hd] [--blocks 256,512]
"""

import functools
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
LANES = 128


# ---------------------------------------------------------------------------
# v2: 128-lane m/l carries
# ---------------------------------------------------------------------------


def _v2_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, causal, scale):
    qi = pl.program_id(1)
    q = q_ref[0]
    block_q, hd = q.shape
    seq_k = k_ref.shape[1]
    num_kb = seq_k // block_k
    reps = block_k // LANES
    q_pos = qi * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    m0 = jnp.full((block_q, LANES), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, LANES), jnp.float32)
    acc0 = jnp.zeros((block_q, hd), jnp.float32)

    def make_body(masked):
        def body(kb, carry):
            m, l, acc = carry
            k = k_ref[0, pl.ds(kb * block_k, block_k), :]
            v = v_ref[0, pl.ds(kb * block_k, block_k), :]
            s = lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            if masked:
                k_pos = kb * block_k + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
            m_cur = jnp.max(s, axis=-1, keepdims=True)      # (bq, 1)
            m_new = jnp.maximum(m, m_cur)                   # (bq, LANES)
            p = jnp.exp(s - jnp.tile(m_new, (1, reps))
                        if reps != 1 else s - m_new)
            corr = jnp.exp(m - m_new)                       # (bq, LANES)
            acc = acc * corr[:, :hd] + lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
            return m_new, l, acc

        return body

    if causal:
        full_upper = lax.div(qi * block_q, block_k)
        upper = jnp.minimum(
            lax.div((qi + 1) * block_q + block_k - 1, block_k), num_kb)
        carry = lax.fori_loop(0, full_upper, make_body(False), (m0, l0, acc0))
        m, l, acc = lax.fori_loop(full_upper, upper, make_body(True), carry)
    else:
        m, l, acc = lax.fori_loop(0, num_kb, make_body(False), (m0, l0, acc0))
    o_ref[0] = (acc / l[:, :hd]).astype(o_ref.dtype)


# ---------------------------------------------------------------------------
# v3: two-pass over a VMEM s-scratch (no streaming corrections)
# ---------------------------------------------------------------------------


def _v3_kernel(q_ref, k_ref, v_ref, o_ref, s_scr, *, block_k, causal, scale):
    qi = pl.program_id(1)
    q = q_ref[0]
    block_q, hd = q.shape
    seq_k = k_ref.shape[1]
    num_kb = seq_k // block_k
    q_pos = qi * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def score(kb, masked):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if masked:
            k_pos = kb * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        return s

    def pass1(masked):
        def body(kb, m):
            s = score(kb, masked)
            s_scr[pl.ds(0, block_q), pl.ds(kb * block_k, block_k)] = s
            return jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        return body

    def pass2(kb, carry):
        l, acc = carry
        s = s_scr[pl.ds(0, block_q), pl.ds(kb * block_k, block_k)]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        p = jnp.exp(s)                                      # s pre-shifted
        l = l + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return l, acc

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    if causal:
        full_upper = lax.div(qi * block_q, block_k)
        upper = jnp.minimum(
            lax.div((qi + 1) * block_q + block_k - 1, block_k), num_kb)
    else:
        full_upper = num_kb
        upper = num_kb
    m = lax.fori_loop(0, full_upper, pass1(False), m0)
    m = lax.fori_loop(full_upper, upper, pass1(True), m)

    # Shift s once in scratch so pass 2 is a bare exp (saves the
    # per-block broadcast-subtract of m).
    def shift(kb, _):
        s_scr[pl.ds(0, block_q), pl.ds(kb * block_k, block_k)] = (
            s_scr[pl.ds(0, block_q), pl.ds(kb * block_k, block_k)] - m
        )
        return 0

    lax.fori_loop(0, upper, shift, 0)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, hd), jnp.float32)
    l, acc = lax.fori_loop(0, upper, pass2, (l0, acc0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


# ---------------------------------------------------------------------------
# v4: single-step full-row softmax (full rectangle, one reduce)
# ---------------------------------------------------------------------------


def _v4_kernel(q_ref, k_ref, v_ref, o_ref, *, causal, scale):
    qi = pl.program_id(1)
    q = q_ref[0]
    block_q, hd = q.shape
    k = k_ref[0]                                            # (t, hd)
    v = v_ref[0]
    t = k.shape[0]
    s = lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                               # (bq, t)
    if causal:
        q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32, (block_q, t), 0)
        k_pos = lax.broadcasted_iota(jnp.int32, (block_q, t), 1)
        s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[0] = (acc / l).astype(o_ref.dtype)


# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------


def _call(kernel_factory, q, k, v, block_q, scratch=None):
    bh, t, hd = q.shape
    full = pl.BlockSpec((1, t, hd), lambda b, i: (b, 0, 0))
    blocked = pl.BlockSpec((1, block_q, hd), lambda b, i: (b, i, 0))
    return pl.pallas_call(
        kernel_factory,
        grid=(bh, t // block_q),
        in_specs=[blocked, full, full],
        out_specs=blocked,
        out_shape=jax.ShapeDtypeStruct((bh, t, hd), q.dtype),
        scratch_shapes=scratch or [],
        interpret=jax.default_backend() != "tpu",
    )(q, k, v)


def variants(t, hd, block_q, block_k, dtype):
    scale = 1.0 / math.sqrt(hd)

    def v1(q, k, v):
        from flexflow_tpu.ops import pallas_kernels as pk
        bh, tt, dd = q.shape
        unfold = lambda x: x.reshape(1, bh, tt, dd)
        return pk.flash_attention(
            unfold(q), unfold(k), unfold(v), True).reshape(bh, tt, dd)

    def v2(q, k, v):
        return _call(
            functools.partial(_v2_kernel, block_k=block_k, causal=True,
                              scale=scale), q, k, v, block_q)

    def v3(q, k, v):
        return _call(
            functools.partial(_v3_kernel, block_k=block_k, causal=True,
                              scale=scale), q, k, v, block_q,
            scratch=[pltpu.VMEM((block_q, t), jnp.float32)])

    def v4(q, k, v):
        return _call(
            functools.partial(_v4_kernel, causal=True, scale=scale),
            q, k, v, block_q)

    def v5_stock(q, k, v):
        # The yardstick (VERDICT r5 item 2): jax's own TPU pallas flash
        # kernel at default block sizes.  TPU-only (no interpret path);
        # the harness's per-variant try/except reports it as FAIL on
        # CPU smoke runs.
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as stock_flash,
        )

        bh, tt, dd = q.shape
        unfold = lambda x: x.reshape(1, bh, tt, dd)
        return stock_flash(
            unfold(q), unfold(k), unfold(v), causal=True, sm_scale=scale
        ).reshape(bh, tt, dd)

    def v6_stream(q, k, v):
        # Streamed 3D-grid formulation (no resident K/V, no VMEM cap
        # on t): K/V blocks arrive via pipelined BlockSpecs; softmax
        # state persists in scratch across the sequential k dimension.
        from flexflow_tpu.ops import pallas_kernels as pk

        bh, tt, dd = q.shape
        unfold = lambda x: x.reshape(1, bh, tt, dd)
        return pk.flash_attention_lse_streamed(
            unfold(q), unfold(k), unfold(v), True,
            block_q=block_q, block_k=block_k,
        )[0].reshape(bh, tt, dd)

    # NOTE: the chunked-decomposition candidate is deliberately NOT in
    # this race: at chunk=256/t=2048 it issues 36 dependent pallas
    # launches per call, so even a short two-point chain would exceed
    # the <=24-call relay-safety cap (MEASURED_r4/README.md).  It races
    # at the fused-train-step level instead, via FF_FLASH_FORCE_CHUNK
    # in tools/profile_lm_decomp.py.
    return {"v1_base": v1, "v2_lanes": v2, "v3_twopass": v3,
            "v4_fullrow": v4, "v5_stock": v5_stock, "v6_stream": v6_stream}


def main():
    from probe_common import chain_slope_ms, parse_dims_blocks

    (b, h, t, hd), blocks = parse_dims_blocks(sys.argv[1:])

    import numpy as np
    key = jax.random.PRNGKey(0)
    shape = (b * h, t, hd)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), shape,
                                 jnp.bfloat16) for i in range(3))
    flops = 2.0 * b * h * t * t * hd  # causal fwd (2 dots, half the square)

    ref = None
    import time
    for block in blocks:
        for name, fn in variants(t, hd, block, block, jnp.bfloat16).items():
            if name in ("v4_fullrow", "v5_stock") and block != blocks[0]:
                continue  # block-size independent (stock picks its own)
            if name == "v2_lanes" and block < LANES:
                continue  # the lane-tile trick needs >= 128-wide blocks
            try:
                jfn = jax.jit(fn)
                out = jfn(q, k, v)
                jax.device_get(out.ravel()[:1])
                got = np.asarray(
                    jax.device_get(out[0, : min(64, t)]), np.float32)
                if ref is None:
                    ref = got
                err = float(np.max(np.abs(got - ref)))

                # Two-point jitted-chain timing: per-call dispatch
                # through the relay costs ms regardless of compute, so
                # single calls sit on a dispatch floor.  One jit'd
                # dependent chain x = f(x) of length N is ONE dispatch;
                # the (N2 - N1) slope cancels both dispatch and the
                # fixed in-chain overheads.  Chains stay <= 16 fwd
                # pallas calls, under the ~30-call dependent chain
                # that once wedged the relay (CLAUDE.md).
                def make_run(n, fn=fn):
                    @jax.jit
                    def run(x):
                        def body(_, x):
                            return fn(x, k, v).astype(x.dtype)
                        return lax.fori_loop(0, n, body, x)
                    return run

                ms = chain_slope_ms(make_run, q, 4, 16)
                print(f"block {block:4d} {name:10s}: {ms:7.2f} ms "
                      f"({flops / (ms * 1e-3) / 1.97e14 * 100:4.1f}% peak) "
                      f"maxerr {err:.3g}", flush=True)
            except Exception as e:
                msg = str(e).split("\n")[0][:200]
                print(f"block {block:4d} {name:10s}: FAIL "
                      f"{type(e).__name__}: {msg}", flush=True)


if __name__ == "__main__":
    main()
