"""Auto-vs-default execution-config A/B on real apps (SEARCH.md).

The acceptance run for ``-s auto`` (ISSUE 6): for each app, train
under the app's hand-written default strategy at the default execution
config (k=1, per-step dispatch), calibrate the dispatch/fence cost
model from that leg's OWN in-memory telemetry, run
``search_execution_config``, then train under the chosen config — a
same-day A/B on the 8-dev virtual CPU mesh (the same methodology as
``tools/measure_superstep.py`` / ``measure_pipeline.py``; run with
``env PYTHONPATH=/root/repo JAX_PLATFORMS=cpu python
tools/measure_search.py``).

The virtual mesh is dispatch-bound at these shapes (one core
multiplexing 8 devices — PIPELINE_OVERHEAD.md), which is exactly the
regime the autotuner's dispatch/fence term models; on the live chip
the same flow runs through ``bench.py``'s ``search`` leg.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

from flexflow_tpu.config import FFConfig
from flexflow_tpu.optim import SGDOptimizer
from flexflow_tpu.runtime.pipeline import make_executor
from flexflow_tpu.runtime.telemetry import Telemetry
from flexflow_tpu.runtime.trainer import Trainer
from flexflow_tpu.search import Calibration, search_execution_config
from flexflow_tpu.search.execution import ExecutionConfig


def _apps(batch: int, nd: int):
    """(name, model, default strategy store) for the A/B apps — the
    apps' own builders and hand-written default strategies, sized to
    the live mesh (``nd`` devices)."""
    out = []

    from flexflow_tpu.models.candle_uno import (
        CandleConfig,
        build_candle_uno,
        candle_uno_strategy,
    )

    candle = CandleConfig(
        dense_layers=[256, 128], dense_feature_layers=[256, 128]
    )
    ff = build_candle_uno(
        batch_size=batch, candle=candle,
        config=FFConfig(batch_size=batch, seed=17),
    )
    out.append(("candle_uno", ff, candle_uno_strategy(nd, candle)))

    from flexflow_tpu.models.dlrm import (
        build_dlrm,
        dlrm_random_benchmark_config,
        dlrm_strategy,
    )

    dcfg = dlrm_random_benchmark_config(num_tables=8)
    dcfg.embedding_size = [2000] * 8  # CPU-mesh scale (bench.py's cut)
    ff = build_dlrm(batch, dcfg, config=FFConfig(batch_size=batch, seed=17))
    out.append(("dlrm", ff, dlrm_strategy(nd, dcfg)))

    from flexflow_tpu.models.alexnet import build_alexnet

    ff = build_alexnet(batch_size=batch, image_size=67, num_classes=10,
                       config=FFConfig(batch_size=batch, seed=17))
    out.append(("alexnet", ff, None))
    return out


def _fit_ms(ex, iters: int, k: int = 1) -> float:
    stats = Trainer(ex).fit(iterations=iters, warmup=2, steps_per_call=k)
    return stats["elapsed_s"] / iters * 1e3


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="measure_search")
    ap.add_argument("-b", "--batch-size", type=int, default=64)
    ap.add_argument("-i", "--iterations", type=int, default=32)
    ap.add_argument("--search-iters", type=int, default=3000)
    args = ap.parse_args(argv)

    nd = len(jax.devices())
    rows = []
    for name, ff, default in _apps(args.batch_size, nd):
        opt = lambda: SGDOptimizer(lr=0.01, momentum=0.9)
        ex = make_executor(ff, default, optimizer=opt())
        with Telemetry() as tel:
            default_ms = _fit_ms(ex, args.iterations)
        cal = Calibration.from_telemetry(tel)
        from flexflow_tpu.parallel.strategy import StrategyStore

        base_store = default or StrategyStore.data_parallel(nd)
        baseline = ExecutionConfig(store=base_store, label="app-default")
        t0 = time.perf_counter()
        res = search_execution_config(
            ff, nd, iters=args.search_iters, seed=0, calibration=cal,
            ks=(1, 2, 4, 8, 16), baseline=baseline,
        )
        wall = time.perf_counter() - t0
        best = res.best
        ex = make_executor(
            ff, best.store if best.store.table else None, optimizer=opt(),
            microbatches=best.microbatches, chunk=best.chunk,
            compiled=best.compiled,
        )
        auto_ms = _fit_ms(ex, args.iterations, k=best.steps_per_call)
        rows.append({
            "app": name,
            "default_ms_per_step": round(default_ms, 3),
            "auto_ms_per_step": round(auto_ms, 3),
            "speedup": round(default_ms / max(auto_ms, 1e-9), 3),
            "auto_config": best.describe(),
            "predicted_ms_per_step": round(best.predicted_ms, 3),
            "search_wall_s": round(wall, 2),
        })
        print(f"{name:12s} default {default_ms:8.3f} ms/step | auto "
              f"{auto_ms:8.3f} ms/step ({rows[-1]['speedup']:.2f}x) | "
              f"{best.describe()} (predicted {best.predicted_ms:.3f}) | "
              f"search {wall:.1f}s", flush=True)
    print(json.dumps({"batch_size": args.batch_size,
                      "iterations": args.iterations, "apps": rows}))
    wins = sum(r["speedup"] > 1.0 for r in rows)
    print(f"auto beats default on {wins}/{len(rows)} apps")
    return 0


if __name__ == "__main__":
    sys.exit(main())
