"""Round-4 flash-BACKWARD kernel variants, raced on the live chip.

The training step spends ~2.5x the forward's attention flops in the
dq/dkv kernels, which carry the same per-block width-1 lane-broadcast
pattern (``exp(s - lse)`` with lse at (bq, 1)) the forward race probes.
Variants:

  b1_prod    the production _bwd_call kernels (control)
  b2_lanes   lse/delta staged at 128-lane width; subtract via jnp.tile

Both run the kernels DIRECTLY (no custom-vjp wrapper): the chain step
is (dq, dk, dv) = bwd(q, ...) with dq fed back as the next q — 2
dependent pallas calls per iteration, chains (2, 8) = 16 calls, under
the <=24-call relay cap (MEASURED_r4/README.md).

Usage: python tools/probe_flash_bwd_variants.py [b h t hd] [--blocks 256,512]
"""

import functools
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from flexflow_tpu.ops import pallas_kernels as pk

LANES = 128


def _dq_kernel_lanes(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                     *, block_k, causal, scale):
    qi = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0]
    block_q, hd = q.shape
    reps = block_k // LANES
    # lse/delta carried at LSE_LANES(=8) lanes; widen once to 128 and
    # tile per block instead of broadcasting a width-1 column per pair.
    lse128 = jnp.tile(lse_ref[0, :, 0:1], (1, LANES))
    delta128 = jnp.tile(delta_ref[0, :, 0:1], (1, LANES))
    seq_k = k_ref.shape[1]
    num_kb = seq_k // block_k
    q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def make_body(masked):
        def body(kb, dq):
            k = k_ref[0, pl.ds(kb * block_k, block_k), :]
            v = v_ref[0, pl.ds(kb * block_k, block_k), :]
            s = lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            if masked:
                k_pos = kb * block_k + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                s = jnp.where(k_pos <= q_pos, s, -1e30)
            p = jnp.exp(s - (jnp.tile(lse128, (1, reps))
                             if reps > 1 else lse128))
            dp = lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - (jnp.tile(delta128, (1, reps))
                            if reps > 1 else delta128))
            return dq + lax.dot_general(
                ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        return body

    dq0 = jnp.zeros((block_q, hd), jnp.float32)
    if causal:
        full_upper = lax.div(qi * block_q, block_k)
        upper = jnp.minimum(
            lax.div((qi + 1) * block_q + block_k - 1, block_k), num_kb)
        dq = lax.fori_loop(0, full_upper, make_body(False), dq0)
        dq = lax.fori_loop(full_upper, upper, make_body(True), dq)
    else:
        dq = lax.fori_loop(0, num_kb, make_body(False), dq0)
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel_lanes(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dk_ref, dv_ref, *, block_q, causal, scale):
    ki = pl.program_id(1)
    k = k_ref[0]
    v = v_ref[0]
    block_k, hd = k.shape
    reps = block_k // LANES
    seq_q = q_ref.shape[1]
    num_qb = seq_q // block_q
    k_pos = ki * block_k + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    def make_body(masked):
        def body(qb, carry):
            dk, dv = carry
            q = q_ref[0, pl.ds(qb * block_q, block_q), :]
            do = do_ref[0, pl.ds(qb * block_q, block_q), :]
            lse128 = jnp.tile(
                lse_ref[0, pl.ds(qb * block_q, block_q), 0:1], (1, LANES))
            delta128 = jnp.tile(
                delta_ref[0, pl.ds(qb * block_q, block_q), 0:1], (1, LANES))
            s = lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            if masked:
                q_pos = qb * block_q + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                s = jnp.where(k_pos <= q_pos, s, -1e30)
            p = jnp.exp(s - (jnp.tile(lse128, (1, reps))
                             if reps > 1 else lse128))
            dv = dv + lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dp = lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - (jnp.tile(delta128, (1, reps))
                            if reps > 1 else delta128))
            dk = dk + lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return dk, dv

        return body

    zeros = (
        jnp.zeros((block_k, hd), jnp.float32),
        jnp.zeros((block_k, hd), jnp.float32),
    )
    if causal:
        lower = lax.div(ki * block_k, block_q)
        first_full = jnp.clip(
            lax.div((ki + 1) * block_k + block_q - 2, block_q), lower, num_qb)
        carry = lax.fori_loop(lower, first_full, make_body(True), zeros)
        dk, dv = lax.fori_loop(first_full, num_qb, make_body(False), carry)
    else:
        dk, dv = lax.fori_loop(0, num_qb, make_body(False), zeros)
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_call_lanes(q, k, v, do, lse, delta, causal, interpret):
    """pk._bwd_call with the lane-width kernels swapped in."""
    bh, t, hd = q.shape
    block_q = pk._require_block(t, hd, q.dtype.itemsize)
    block_k = block_q
    scale = 1.0 / math.sqrt(hd)
    L = pk.LSE_LANES
    full = pl.BlockSpec((1, t, hd), lambda b, i: (b, 0, 0))
    full_r = pl.BlockSpec((1, t, L), lambda b, i: (b, 0, 0))
    q_blocked = pl.BlockSpec((1, block_q, hd), lambda b, i: (b, i, 0))
    q_blocked_r = pl.BlockSpec((1, block_q, L), lambda b, i: (b, i, 0))
    k_blocked = pl.BlockSpec((1, block_k, hd), lambda b, i: (b, i, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel_lanes, block_k=block_k, causal=causal,
                          scale=scale),
        grid=(bh, t // block_q),
        in_specs=[q_blocked, full, full, q_blocked, q_blocked_r, q_blocked_r],
        out_specs=q_blocked,
        out_shape=jax.ShapeDtypeStruct((bh, t, hd), q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel_lanes, block_q=block_q, causal=causal,
                          scale=scale),
        grid=(bh, t // block_k),
        in_specs=[full, k_blocked, k_blocked, full, full_r, full_r],
        out_specs=[k_blocked, k_blocked],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, hd), k.dtype),
            jax.ShapeDtypeStruct((bh, t, hd), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def main():
    from probe_common import chain_slope_ms, parse_dims_blocks

    (b, h, t, hd), blocks = parse_dims_blocks(sys.argv[1:])

    import numpy as np
    interpret = jax.default_backend() != "tpu"
    key = jax.random.PRNGKey(0)
    shape = (b * h, t, hd)
    q, k, v, do = (jax.random.normal(jax.random.fold_in(key, i), shape,
                                     jnp.bfloat16) for i in range(4))
    # bwd flops (causal): dq (3 dots) + dkv (4 dots) over half the square.
    flops = 7.0 * b * h * t * t * hd

    for block in blocks:
        os.environ["FF_FLASH_BLOCK"] = str(block)
        import importlib
        importlib.reload(pk)  # re-read the block target
        o, lse = pk._fwd_call(q, k, v, True, interpret)
        delta = jnp.broadcast_to(
            jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1,
                    keepdims=True), (b * h, t, pk.LSE_LANES))

        variants = {
            "b1_prod": lambda x: pk._bwd_call(
                x, k, v, do, lse, delta, True, interpret),
            # Streamed 3D-grid dq/dkv (no resident K/V — the backward
            # half of the v6_stream formulation).
            "b3_stream": lambda x: pk._bwd_stream_call(
                x, k, v, do, lse, delta, True, interpret,
                block_q=block, block_k=block),
        }
        if block >= LANES:  # the lane-tile trick needs >= 128-wide blocks
            variants["b2_lanes"] = lambda x: _bwd_call_lanes(
                x, k, v, do, lse, delta, True, interpret)
        ref = None
        for name, fn in variants.items():
            try:
                out = jax.jit(fn)(q)
                jax.device_get(out[0].ravel()[:1])
                # Validate ALL THREE cotangents (dq, dk, dv) — a broken
                # dkv kernel must not win the race on a dq-only check.
                got = np.concatenate([
                    np.asarray(jax.device_get(o[0, :64]), np.float32)
                    for o in out
                ])
                if ref is None:
                    ref = got
                err = float(np.max(np.abs(got - ref)))

                def make_run(n, fn=fn):
                    @jax.jit
                    def run(x):
                        def body(_, x):
                            dq, dk, dv = fn(x)
                            return (dq + dk + dv).astype(x.dtype)
                        return lax.fori_loop(0, n, body, x)
                    return run

                # 2 pallas calls/iter -> 16-call chain max (cap <= 24).
                ms = chain_slope_ms(make_run, q, 2, 8)
                print(f"block {block:4d} {name:8s}: {ms:7.2f} ms "
                      f"({flops / (ms * 1e-3) / 1.97e14 * 100:4.1f}% peak) "
                      f"maxerr {err:.3g}", flush=True)
            except Exception as e:
                msg = str(e).split("\n")[0][:200]
                print(f"block {block:4d} {name:8s}: FAIL "
                      f"{type(e).__name__}: {msg}", flush=True)


if __name__ == "__main__":
    main()
