"""Decompose the transformer-LM step time on the live chip.

The bench headline (round 2: 113k tokens/s, MFU 0.166 at b16/t2048/L6)
leaves ~45% of the step unexplained by the analytic flop budget at
plausible kernel efficiencies.  This tool measures, in fresh
subprocesses (relay-safe):

  L=1 vs L=6 at b16   -> per-transformer-block ms (slope) and the
                         embed+head+xent+optimizer intercept
  b32 + --remat at L6 -> whether rematerialization unlocks the larger
                         batch (round-2 sweep: b32 OOM'd) and what it
                         yields in tokens/s

Usage: python tools/profile_lm_decomp.py
"""

import os
import subprocess
import sys

BODY = r"""
import sys, time
import jax
layers, batch, remat, seq_arg = (int(x) for x in sys.argv[1:5])

from flexflow_tpu.config import FFConfig
from flexflow_tpu.models.transformer import build_transformer_lm
from flexflow_tpu.optim import AdamOptimizer
from flexflow_tpu.runtime.executor import Executor
from flexflow_tpu.runtime.trainer import Trainer

import os
smoke = os.environ.get("FF_DECOMP_SMOKE") == "1"
seq, vocab, d, iters = ((128, 512, 64, 3) if smoke
                        else (2048, 32768, 512, 12))
if seq_arg and not smoke:
    seq = seq_arg
    iters = max(3, iters // max(1, seq // 2048))
cfg = FFConfig(batch_size=batch, compute_dtype="bfloat16", remat=bool(remat))
ff = build_transformer_lm(batch_size=batch, seq_len=seq, vocab_size=vocab,
                          d_model=d, num_heads=8, num_layers=layers,
                          config=cfg)
ex = Executor(ff, optimizer=AdamOptimizer(lr=1e-4),
              devices=jax.devices()[:1])
stats = Trainer(ex).fit(iterations=iters, warmup=1 if smoke else 3)
ms = 1e3 / (stats["samples_per_s"] / batch)
chunk = os.environ.get("FF_FLASH_FORCE_CHUNK", "0")
print(f"RESULT L={layers} b={batch} seq={seq} remat={remat} chunk={chunk}: "
      f"{ms:8.1f} ms/step  {stats['samples_per_s'] * seq:,.0f} tokens/s",
      flush=True)
"""


def main():
    os.chdir(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    # (layers, batch, remat, seq, chunk): seq=0 keeps the default 2048;
    # chunk>0 exports FF_FLASH_FORCE_CHUNK, racing the chunked flash
    # decomposition against the monolithic kernel INSIDE the fused
    # train step (the relay-safe way — a pallas-chain microbench of the
    # 36-launch chunked call would blow the <=24-call cap).  The
    # seq=16384 row drives the chunked path at its real scale (past the
    # single-launch VMEM cap).
    for layers, batch, remat, seq, chunk in (
        (1, 16, 0, 0, 0), (6, 16, 0, 0, 0), (6, 16, 0, 0, 512),
        (6, 16, 0, 0, 1024), (6, 32, 1, 0, 0), (6, 1, 0, 16384, 0),
    ):
        env = dict(os.environ)
        if chunk:
            env["FF_FLASH_FORCE_CHUNK"] = str(chunk)
        r = subprocess.run(
            [sys.executable, "-c", BODY,
             str(layers), str(batch), str(remat), str(seq)],
            text=True, capture_output=True, env=env,
        )
        for line in (r.stdout + r.stderr).splitlines():
            if line.startswith("RESULT") or "rror" in line[:60]:
                print(line, flush=True)
        if r.returncode != 0 and "RESULT" not in r.stdout:
            tail = (r.stderr or r.stdout).strip().splitlines()[-3:]
            print(f"FAIL L={layers} b={batch} remat={remat} seq={seq}: "
                  + " | ".join(tail), flush=True)


if __name__ == "__main__":
    main()
