"""Round-4 TPU probes: bf16 row-DMA kernels and long-context flash.

Each probe compiles+runs one small kernel on the live chip and prints
PASS/FAIL — run BEFORE committing defaults that route new dtypes or
shapes onto Mosaic (CPU interpret-mode tests cannot catch Mosaic
rejects).  Run with PYTHONPATH=/root/.axon_site:/root/repo.
"""

import jax
import jax.numpy as jnp
import numpy as np


def probe(name, fn):
    try:
        fn()
        print(f"PASS {name}")
    except Exception as e:
        msg = str(e).split("\n")[0][:300]
        print(f"FAIL {name}: {type(e).__name__}: {msg}")


def rows_bf16_gated():
    # Measured outcome, kept as a regression probe: Mosaic rejects
    # dynamic one-row slices on packed bf16 sublanes ("index in
    # dimension 0 is a multiple of 4"), so the row kernels are
    # f32-only and the gate must route bf16 tables to the dense path.
    from flexflow_tpu.ops import pallas_kernels as pk

    for d in (64, 128):
        assert not pk.rows_supported(4, d, jnp.bfloat16, num_rows=1024), (
            f"rows_supported admits bf16 d={d}, which Mosaic rejects"
        )


def rows_f32():
    from flexflow_tpu.ops import pallas_kernels as pk

    table = jnp.zeros((1024, 64), jnp.float32)
    idx = jnp.array([3, 7, 3, 100], jnp.int32)
    upd = jnp.ones((4, 64), jnp.float32)
    out = pk.scatter_add_rows(table, idx, upd)
    got = jax.device_get(out[jnp.array([3, 7, 100])])
    want = np.zeros((3, 64), np.float32)
    want[0] = 2.0
    want[1] = 1.0
    want[2] = 1.0
    np.testing.assert_allclose(got, want)
    g = pk.gather_rows(out, idx)
    np.testing.assert_allclose(jax.device_get(g[0]), got[0])
    # Duplicate rows at every pipeline distance (the double-buffered
    # scatter's hazard classes: adjacent run, distance-2, far).
    idx2 = jnp.array([3, 3, 3, 7, 3, 9, 3, 11, 12, 3], jnp.int32)
    upd2 = jnp.arange(10 * 64, dtype=jnp.float32).reshape(10, 64)
    out2 = pk.scatter_add_rows(jnp.zeros((64, 64), jnp.float32), idx2, upd2)
    ref2 = np.zeros((64, 64), np.float32)
    np.add.at(ref2, np.asarray(idx2), np.asarray(upd2))
    np.testing.assert_allclose(jax.device_get(out2), ref2)


def flash_8k(dtype, b):
    from flexflow_tpu.ops import pallas_kernels as pk

    shape = (b, 8, 8192, 64)
    assert pk.flash_supported(shape, dtype), "gate rejected the probe shape"
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), shape, dtype)
               for i in range(3))

    def loss(q, k, v):
        return jnp.sum(pk.flash_attention(q, k, v, True).astype(jnp.float32))

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    jax.device_get(g[0].ravel()[:1])


def flash_16k_chunked():
    # bf16 t=16384 exceeds the single-launch VMEM cap; the chunked
    # decomposition (8192-chunks + lse merges) must compile and train.
    from flexflow_tpu.ops import pallas_kernels as pk

    shape = (1, 4, 16384, 64)
    assert pk.flash_chunked_supported(shape, jnp.bfloat16)
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), shape,
                                 jnp.bfloat16) for i in range(3))

    def loss(q, k, v):
        out, _ = pk.flash_attention_lse_chunked(q, k, v, True)
        return jnp.sum(out.astype(jnp.float32))

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    val = jax.device_get(g[0].ravel()[:1])
    assert np.isfinite(val).all()


def flash_32k_chunked():
    # Round-5 (VERDICT item 7): t=32768 = 4 x 8192 kernel chunks —
    # the transformer_32k bench leg's exact dispatch path, fwd + bwd.
    from flexflow_tpu.ops import pallas_kernels as pk

    shape = (1, 2, 32768, 64)
    assert pk.flash_chunked_supported(shape, jnp.bfloat16)
    key = jax.random.PRNGKey(1)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), shape,
                                 jnp.bfloat16) for i in range(3))

    def loss(q, k, v):
        out, _ = pk.flash_attention_lse_chunked(q, k, v, True)
        return jnp.sum(out.astype(jnp.float32))

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    val = jax.device_get(g[0].ravel()[:1])
    assert np.isfinite(val).all()


def scatter_empty_batch():
    # Round-5 ADVICE fix: n=0 must no-op on hardware too (Python-level
    # guard, but the jit cache path around it must hold).
    from flexflow_tpu.ops import pallas_kernels as pk

    table = jnp.ones((256, 128), jnp.float32)
    out = jax.jit(pk.scatter_add_rows)(
        table, jnp.zeros((0,), jnp.int32), jnp.zeros((0, 128), jnp.float32)
    )
    assert jax.device_get(out[0, 0]) == 1.0


def blocked_ragged_t():
    # Round-5: the jnp blocked long-context fallback at a ragged t no
    # kernel decomposes (8200); must compile and train on TPU.
    from flexflow_tpu.ops import pallas_kernels as pk

    shape = (1, 2, 8200, 64)
    assert not pk.flash_chunked_supported(shape, jnp.bfloat16)
    assert pk.blocked_attention_applies(shape)
    key = jax.random.PRNGKey(2)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), shape,
                                 jnp.bfloat16) for i in range(3))

    def loss(q, k, v):
        out, _ = pk.attention_lse_blocked(q, k, v, True)
        return jnp.sum(out.astype(jnp.float32))

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    val = jax.device_get(g[0].ravel()[:1])
    assert np.isfinite(val).all()


def flash_streamed_16k():
    # Round-5 candidate: the streamed 3D-grid forward at a t the
    # resident-K/V kernel cannot launch (bf16 t=16384 single launch).
    # Mosaic legality + numerics vs the chunked decomposition.
    from flexflow_tpu.ops import pallas_kernels as pk

    shape = (1, 2, 16384, 64)
    assert not pk.flash_supported(shape, jnp.bfloat16)
    key = jax.random.PRNGKey(3)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), shape,
                                 jnp.bfloat16) for i in range(3))
    o_s, _ = jax.jit(
        lambda q, k, v: pk.flash_attention_lse_streamed(q, k, v, True)
    )(q, k, v)
    o_c, _ = jax.jit(
        lambda q, k, v: pk.flash_attention_lse_chunked(q, k, v, True)
    )(q, k, v)
    # Streamed backward at the same t (Mosaic legality; numerics are
    # interpret-pinned in tests/test_pallas.py).
    bh = shape[0] * shape[1]
    fold = lambda x: x.reshape(bh, shape[2], shape[3])
    lse_l = jnp.zeros((bh, shape[2], pk.LSE_LANES), jnp.float32)
    dq, dk, dv = jax.jit(
        lambda a, b_, c: pk._bwd_stream_call(
            a, b_, c, a, lse_l, lse_l, True, False)
    )(fold(q), fold(k), fold(v))
    assert np.isfinite(
        np.asarray(jax.device_get(dq[0, -8:]), np.float32)).all()
    # Tail rows: under causal masking they attend across ALL k-blocks,
    # so this exercises the streamed kernel's cross-block softmax
    # carry (head rows complete inside the first block and would pass
    # even with a broken carry).
    a = np.asarray(jax.device_get(o_s[:, :, -64:]), np.float32)
    b = np.asarray(jax.device_get(o_c[:, :, -64:]), np.float32)
    assert np.isfinite(a).all() and np.max(np.abs(a - b)) < 3e-2, (
        np.max(np.abs(a - b))
    )


def flash_f32_8k_gated():
    # Measured outcome, kept as a regression probe: f32 at t=8192
    # (u = 2 MB per operand) OOMs scoped VMEM at EVERY block size
    # (16.5-24 MB vs the 16 MB limit), so the gate must reject it.
    from flexflow_tpu.ops import pallas_kernels as pk

    assert not pk.flash_supported((2, 8, 8192, 64), jnp.float32), (
        "gate admits a shape the v5e compile matrix proved un-compilable"
    )


def main():
    print(f"backend: {jax.default_backend()}, devices: {len(jax.devices())}")
    probe("rows bf16 gated off", rows_bf16_gated)
    probe("scatter/gather rows f32 d=64", rows_f32)
    probe("flash fwd+bwd bf16 t=8192", lambda: flash_8k(jnp.bfloat16, 4))
    probe("flash f32 t=8192 gated off", flash_f32_8k_gated)
    probe("flash chunked bf16 t=16384", flash_16k_chunked)
    probe("flash chunked bf16 t=32768", flash_32k_chunked)
    probe("scatter empty batch no-op", scatter_empty_batch)
    probe("blocked attention ragged t=8200", blocked_ragged_t)
    probe("streamed flash bf16 t=16384", flash_streamed_16k)


if __name__ == "__main__":
    main()
