"""Calibrate ffsim against the chip (VERDICT r4 item 3).

The reference's simulator lived and died by measured times
(``scripts/cnn.h:204-260``, ``simulator.cc:142-151``): per-config
microbenchmarks anchored every simulated makespan.  This repo's
per-(op,degree) table is measured the same way, but the END-TO-END
simulated step time had never been compared to a measured fused step —
so the ``*_speedup_sim`` numbers were internally consistent yet
externally unanchored.

This tool closes the loop on the one device we can reach: for
alexnet (bench.py's headline b=2048 config) / vgg16 (search shape,
b=64 — it has no bench leg) / dlrm (run_random.sh shape) it
  1. measures the per-(op, degree=1) fwd+bwd table live,
  2. predicts the single-chip step via ffsim in BOTH pricing modes
     (measured table / analytic roofline),
  3. measures the real fused ``Trainer.fit`` step (host-readback
     fenced, reference formula), and
  4. prints percent error of each prediction vs the fused step.

Interpretation: the measured-mode error isolates what ffsim's
sum-of-parts model misses (XLA cross-op fusion, optimizer, dispatch);
the roofline-mode error additionally includes the device-model
constants — tune those (``search/cost_model.py DeviceModel``) until
the roofline column lands <20%.  Results land in OP_PARALLEL.md.
"""
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _models(on_tpu: bool):
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.models.alexnet import build_alexnet
    from flexflow_tpu.models.cnn_catalog import build_vgg16
    from flexflow_tpu.models.dlrm import (
        build_dlrm,
        dlrm_random_benchmark_config,
    )

    out = []
    # bench.py's headline alexnet config (BENCH_BATCH default 2048) —
    # the calibration must anchor the shape the bench reports; vgg16
    # has no bench leg, so it runs at its search shape (b=64).
    b = 2048 if on_tpu else 16
    cfg = FFConfig(batch_size=b, compute_dtype="bfloat16")
    out.append(("alexnet", build_alexnet(
        batch_size=b, image_size=229 if on_tpu else 64,
        num_classes=1000, config=cfg)))
    bv = 64 if on_tpu else 8
    out.append(("vgg16", build_vgg16(
        batch_size=bv, image_size=224 if on_tpu else 64,
        config=FFConfig(batch_size=bv, compute_dtype="bfloat16"))))
    dcfg = dlrm_random_benchmark_config(num_tables=8)
    if not on_tpu:
        dcfg.embedding_size = [10000] * 8
    bd = 256
    out.append(("dlrm", build_dlrm(
        bd, dcfg, config=FFConfig(batch_size=bd, compute_dtype="bfloat16"))))
    return out


def main():
    # Probe the tunnel in a timeout-bounded subprocess BEFORE any
    # in-process backend touch (bench.py's relay-proofing: a wedged
    # relay hangs jax init and must never be timeout-killed).
    import bench

    platform, _, probe_err = bench.probe_backend()
    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        if probe_err:
            print(f"tunnel down ({probe_err}); calibrating plumbing on "
                  f"CPU — numbers are NOT chip data", file=sys.stderr)

    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from flexflow_tpu.optim import SGDOptimizer
    from flexflow_tpu.parallel.strategy import StrategyStore
    from flexflow_tpu.runtime.executor import Executor
    from flexflow_tpu.runtime.profiler import measured_degree_table
    from flexflow_tpu.runtime.trainer import Trainer
    from flexflow_tpu.search import simulate_strategy

    on_tpu = jax.default_backend() != "cpu"
    iters = 20 if on_tpu else 3
    rows = []
    for name, ff in _models(on_tpu):
        t0 = time.time()
        table = measured_degree_table(ff, num_devices=1)
        dp1 = StrategyStore(1)
        sim_meas_us = simulate_strategy(ff, dp1, 1, measured_costs=table)
        sim_roof_us = simulate_strategy(ff, dp1, 1)
        ex = Executor(ff, optimizer=SGDOptimizer(lr=0.01),
                      devices=jax.devices()[:1])
        stats = Trainer(ex).fit(iterations=iters, warmup=3)
        step_us = stats["elapsed_s"] / iters * 1e6
        err = lambda sim: (sim - step_us) / step_us * 100.0
        row = {
            "model": name,
            "measured_step_us": round(step_us, 1),
            "sim_measured_us": round(sim_meas_us, 1),
            "sim_roofline_us": round(sim_roof_us, 1),
            "err_measured_pct": round(err(sim_meas_us), 1),
            "err_roofline_pct": round(err(sim_roof_us), 1),
            "platform": jax.default_backend(),
            "wall_s": round(time.time() - t0, 1),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)
    print("CALIBRATION " + json.dumps(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
