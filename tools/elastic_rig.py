#!/usr/bin/env python
"""Elastic multi-host training rig (RESILIENCE.md "Host loss & elastic
resize").

Launches an N-process CPU ``jax.distributed`` world — coordinator +
workers, each a FRESH subprocess with its own virtual device slice —
running real training through ``build_hybrid_mesh_plan`` with per-host
loader shards, supervised by ``flexflow_tpu.runtime.elastic.run_rig``:
a SIGKILLed worker is classified ``host_loss`` and the survivors are
relaunched one process smaller against the same checkpoint directory
(elastic resize); a SIGKILLed process 0 is ``coordinator_loss`` and
the same world restarts under a fresh coordinator, within the restart
budget.

Usage:
  python tools/elastic_rig.py --world 2 --ckpt-dir /tmp/rig
  python tools/elastic_rig.py --world 2 --ckpt-dir /tmp/rig \
      --kill-worker-at 11 --telemetry /tmp/rig/tel
  python tools/elastic_rig.py --worker       # one rig process, env-driven

``--worker`` is the per-process entry (``JAX_COORDINATOR_ADDRESS`` /
``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID`` + ``FF_ELASTIC_*`` in the
environment, exactly what the launcher sets) — the hook for driving
the same protocol from a real multi-node scheduler.

Exit code 0 iff the run completed within the restart budget.  The
launcher never initializes a jax backend itself; it re-execs into a
clean CPU child first so the axon sitecustomize's forced TPU relay
(CLAUDE.md environment hazards) cannot leak into the worker tree.
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def parse_args(argv):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--worker", action="store_true",
                    help="run one env-driven rig process (launcher use)")
    ap.add_argument("--world", type=int, default=2,
                    help="initial world size (processes)")
    ap.add_argument("--ckpt-dir", default="",
                    help="checkpoint directory (required; shared by "
                         "every generation — the elastic handoff point)")
    ap.add_argument("--iters", type=int, default=16)
    ap.add_argument("--k", type=int, default=8,
                    help="steps per superstep dispatch")
    ap.add_argument("--save-every", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--global-batch", type=int, default=8)
    ap.add_argument("--devices-per-host", type=int, default=4)
    ap.add_argument("--kill-worker-at", type=int, default=0, metavar="STEP",
                    help="SIGKILL the last worker at STEP (host_loss)")
    ap.add_argument("--kill-coordinator-at", type=int, default=0,
                    metavar="STEP",
                    help="SIGKILL process 0 at STEP (coordinator_loss)")
    ap.add_argument("--max-restarts", type=int, default=3)
    ap.add_argument("--telemetry", default="",
                    help="telemetry dir (one JSONL stream per process "
                         "per generation, -p<id> suffixed)")
    ap.add_argument("--grace", type=float, default=30.0,
                    help="seconds before wedged survivors are reclaimed "
                         "(gloo collectives have no timeout)")
    ap.add_argument("--timeout", type=float, default=420.0)
    return ap.parse_args(argv)


def parent(argv):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO  # drop /root/.axon_site: no TPU relay
    return subprocess.call(
        [sys.executable, os.path.abspath(__file__), "--child"] + argv,
        env=env,
    )


def child(argv):
    args = parse_args(argv)
    if args.worker:
        from flexflow_tpu.runtime.elastic import worker_main

        worker_main()  # exits via os._exit, never returns
        return 0
    if not args.ckpt_dir:
        print("--ckpt-dir is required", file=sys.stderr)
        return 2
    if args.kill_worker_at and args.kill_coordinator_at:
        print("--kill-worker-at and --kill-coordinator-at are mutually "
              "exclusive (one fault per rig run)", file=sys.stderr)
        return 2
    from flexflow_tpu.runtime.elastic import RigFailure, run_rig

    kill_process, kill_at = None, 0
    if args.kill_worker_at:
        kill_process, kill_at = args.world - 1, args.kill_worker_at
    elif args.kill_coordinator_at:
        kill_process, kill_at = 0, args.kill_coordinator_at
    try:
        out = run_rig(
            args.world, args.ckpt_dir,
            iters=args.iters, k=args.k, save_every=args.save_every,
            seed=args.seed, global_batch=args.global_batch,
            devices_per_host=args.devices_per_host,
            kill_process=kill_process, kill_at_step=kill_at,
            max_restarts=args.max_restarts,
            telemetry_dir=args.telemetry or None,
            timeout_s=args.timeout, grace_s=args.grace,
        )
    except RigFailure as e:
        print(f"elastic_rig: {e}", file=sys.stderr)
        return 1
    print(json.dumps(out, indent=2, default=str))
    return 0


def main():
    argv = sys.argv[1:]
    if "--child" in argv:
        argv.remove("--child")
        return child(argv)
    # --worker must NOT be re-wrapped: the launcher already built its
    # environment (coordinator address, device count, telemetry).
    if "--worker" in argv:
        return child(argv)
    return parent(argv)


if __name__ == "__main__":
    sys.exit(main())
