"""Fused-step race: production flash dispatch vs FF_FLASH_STREAMED=1.

Per-kernel chain timing (probe_flash_variants) ranks the kernels; this
races them where it counts — the full jitted LM train step through
Trainer.fit, the only measurement the relay cannot distort
(MEASURED_r4/README.md).  Each arm runs in a FRESH subprocess because
the dispatch flag is read at module import; ABAB interleave splits
drift from effect.

Usage: python tools/race_streamed_step.py [iters]
"""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_ARM = r"""
import sys
sys.path.insert(0, {repo!r})
from bench import _bench_lm, probe_backend
import os, jax
platform, _, err = probe_backend()
if platform == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
on_tpu = platform != "cpu"
tps, mfu = _bench_lm(batch=16 if on_tpu else 2,
                     seq=2048 if on_tpu else 256,
                     layers=6 if on_tpu else 2,
                     iters={iters} if on_tpu else 2)
print(f"RESULT tokens_per_s={{tps:.1f}} mfu={{mfu:.4f}} "
      f"platform={{jax.default_backend()}}", file=sys.stderr)
"""


def run_arm(streamed: bool, iters: int) -> str:
    env = dict(os.environ)
    env["FF_FLASH_STREAMED"] = "1" if streamed else "0"
    # TPU-path PYTHONPATH must KEEP the axon sitecustomize (CLAUDE.md:
    # dropping it leaves JAX_PLATFORMS=axon pointing at an unregistered
    # backend and every jax init fails).
    env.setdefault("PYTHONPATH", f"/root/.axon_site:{REPO}")
    out = subprocess.run(
        [sys.executable, "-c", _ARM.format(repo=REPO, iters=iters)],
        capture_output=True, text=True, env=env, cwd=REPO,
    )
    for line in (out.stderr or "").splitlines():
        if line.startswith("RESULT"):
            return line
    return f"FAIL rc={out.returncode}: {(out.stderr or '')[-300:]}"


def main():
    iters = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    ok = 0
    for arm in (False, True, False, True):
        name = "streamed" if arm else "production"
        line = run_arm(arm, iters)
        ok += line.startswith("RESULT")
        print(f"{name:10s} {line}", flush=True)
    # A race where no arm produced data must not log rc=0 in the
    # measurement sequence.
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
