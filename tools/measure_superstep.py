"""Superstep A/B: per-step wall time of Trainer.fit at k steps/dispatch.

ISSUE 1 acceptance harness: at a dispatch-bound shape (a model whose
step compute is far below the per-dispatch host cost) the superstep
path (``Executor.build_superstep``: K train steps fused into one jitted
``lax.scan`` with one host-readback fence per call) must show per-step
wall time strictly decreasing from k=1 to k=8.  On CPU the per-dispatch
overhead is ~100 us; through the axon relay it is ~16 ms, so the same
sweep on chip (bench.py's superstep leg) amortizes proportionally more.

Runs on CPU by default (A/B numbers must not depend on the tunnel);
pass --tpu to skip the CPU pin and measure the live backend instead.
Prints per-arm lines on stderr and ONE JSON summary line on stdout.
"""

import json
import os
import sys

if "--tpu" not in sys.argv:
    os.environ["JAX_PLATFORMS"] = "cpu"
    # The 8-device virtual mesh (the repo's test environment): each
    # dispatch launches the executable on 8 virtual devices of ONE
    # core, putting the per-dispatch host cost near 1 ms — a faithful
    # stand-in for the relay's per-call floor.
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax

if "--tpu" not in sys.argv:
    # The axon sitecustomize overrides JAX_PLATFORMS at interpreter
    # start; pin the config back before any backend init (CLAUDE.md).
    jax.config.update("jax_platforms", "cpu")

import numpy as np


def build_executor():
    """Dispatch-bound shape: a 2-layer b=32 MLP whose whole step is
    tens of microseconds of compute — per-step time is dominated by
    dispatch + fence, exactly what supersteps amortize."""
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.graph import FFModel
    from flexflow_tpu.optim import SGDOptimizer
    from flexflow_tpu.runtime.executor import Executor

    batch = 32
    ff = FFModel(FFConfig(batch_size=batch, seed=3))
    x = ff.create_tensor((batch, 64), name="x")
    lbl = ff.create_tensor((batch,), dtype=np.int32, name="label")
    t = ff.dense(x, 64, activation="relu", name="fc1")
    t = ff.dense(t, 8, name="fc2")
    ff.softmax(t, lbl, name="softmax")
    return Executor(ff, optimizer=SGDOptimizer(lr=0.01, momentum=0.9))


def main():
    import contextlib

    from flexflow_tpu.runtime.trainer import Trainer

    ks = (1, 2, 4, 8, 16)
    iters = 64  # divisible by every k: no remainder recompile
    reps = 3
    best_ms = {}
    ex = build_executor()
    # Interleaved rounds (ABAB) split host drift from the k effect;
    # per-k jit caches live on the executor, so later rounds re-time
    # the same compiled program.  Trainer.fit prints its reference
    # timing lines on stdout — route them to stderr so stdout stays
    # one JSON line.
    for rep in range(reps):
        for k in ks:
            with contextlib.redirect_stdout(sys.stderr):
                stats = Trainer(ex).fit(iterations=iters, warmup=1,
                                        steps_per_call=k)
            ms = stats["elapsed_s"] / iters * 1e3
            best_ms[k] = min(best_ms.get(k, float("inf")), ms)
            print(f"rep {rep} k={k:2d}: {ms:8.3f} ms/step",
                  file=sys.stderr)
    k1 = best_ms[1]
    summary = {
        "metric": "superstep_ms_per_step",
        "platform": jax.default_backend(),
        "batch_size": 32,
        "iterations": iters,
        "ms_per_step": {f"k{k}": round(best_ms[k], 4) for k in ks},
        "amortization_vs_k1": {
            f"k{k}": round(k1 / best_ms[k], 3) for k in ks if k > 1
        },
        "strictly_decreasing_to_k8": best_ms[1] > best_ms[2] > best_ms[4]
        > best_ms[8],
    }
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
